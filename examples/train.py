"""End-to-end resumable trainer over the overlapped kernel stack.

The user story the reference never ships (it stops at kernels): a training
CLI that wires every framework subsystem together —

- model families: dense Llama (TP; every projection through the overlapped
  AG-GEMM / GEMM-RS kernels) or Mixtral-class MoE (EP AllToAll + grouped
  GEMM, differentiable);
- mesh: 1-D tp or 2-D dp×tp (`--dp`), built from however many devices the
  process sees;
- checkpoint/resume: `runtime.CheckpointManager` — kill the process at any
  step and re-run the same command to continue bit-exactly;
- failure detection: `runtime.Heartbeat` liveness file + per-step stall
  watchdog around the device computation;
- observability: `--profile` wraps the loop in `runtime.group_profile`.

Runs anywhere, TPU or the virtual CPU mesh:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train.py --model moe --dp 2 --steps 20 \
      --ckpt-dir /tmp/run1 --ckpt-every 5
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--model", choices=("llama", "moe"), default="llama")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dp", type=int, default=1, help="data-parallel degree")
    p.add_argument("--batch", type=int, default=4, help="global batch")
    p.add_argument("--seq", type=int, default=64, help="sequence length")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--opt", choices=("sgd", "adamw"), default="sgd",
                   help="sgd = the families' fused step; adamw = optax "
                        "(models/training.py), opt state checkpointed too")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=10)
    p.add_argument("--keep", type=int, default=3)
    p.add_argument("--step-timeout", type=float, default=600.0,
                   help="per-step stall watchdog (seconds)")
    p.add_argument("--heartbeat", default=None,
                   help="liveness file path (default: <ckpt-dir>/heartbeat)")
    p.add_argument("--profile", default=None,
                   help="profile trace directory")
    p.add_argument("--impl", default="auto",
                   choices=("auto", "xla", "pallas"))
    return p.parse_args()


def build(args, mesh, axis, dp_axis):
    """(cfg, params, step_fn, specs) for the chosen family."""
    tp = mesh.shape[axis]
    if args.model == "llama":
        from triton_dist_tpu.models import llama as fam
        cfg = fam.LlamaConfig(vocab=256, dim=32 * tp, n_layers=2,
                              n_heads=tp, n_kv_heads=tp, ffn_dim=128 * tp,
                              max_seq=max(args.seq, 64), dtype=jnp.float32)
    else:
        from triton_dist_tpu.models import moe as fam
        cfg = fam.MoEConfig(vocab=256, dim=32 * tp, n_layers=2,
                            n_heads=tp, n_kv_heads=tp,
                            n_experts=2 * tp, topk=2, expert_ffn_dim=64,
                            max_seq=max(args.seq, 64), block_m=8,
                            dtype=jnp.float32)
    params = fam.place_params(
        fam.init_params(cfg, jax.random.key(args.seed)), cfg, mesh)
    if args.opt == "adamw":
        import optax

        from triton_dist_tpu.models import training
        opt_step, opt_init = training.make_optax_train_step(
            fam, cfg, mesh, optax.adamw(args.lr), axis=axis,
            dp_axis=dp_axis, impl=args.impl)
        state = {"params": params, "opt": opt_init(params)}

        def step_fn(st, tokens, targets):
            p, o, loss = opt_step(st["params"], st["opt"], tokens, targets)
            return {"params": p, "opt": o}, loss
    else:
        sgd_step, _specs = fam.make_train_step(cfg, mesh, axis=axis,
                                               dp_axis=dp_axis,
                                               impl=args.impl, lr=args.lr)
        state = {"params": params}

        def step_fn(st, tokens, targets):
            p, loss = sgd_step(st["params"], tokens, targets)
            return {"params": p}, loss
    return cfg, state, step_fn


def main():
    args = parse_args()
    from triton_dist_tpu.runtime import (
        CheckpointManager, Heartbeat, block_until_ready_with_timeout,
        dist_print, group_profile, initialize_distributed)

    initialize_distributed()
    n = jax.device_count()
    assert n % args.dp == 0, (n, args.dp)
    tp = n // args.dp
    if args.dp > 1:
        mesh = Mesh(np.array(jax.devices()).reshape(args.dp, tp),
                    ("dp", "tp"))
        dp_axis = "dp"
    else:
        mesh = Mesh(np.array(jax.devices()), ("tp",))
        dp_axis = None
    axis = "tp"
    dist_print(f"mesh {dict(mesh.shape)}  model={args.model}")

    cfg, state, step_fn = build(args, mesh, axis, dp_axis)

    # Deterministic toy data: next-token prediction on a fixed random book.
    key = jax.random.key(args.seed + 1)
    batch_spec = P(axis, dp_axis) if dp_axis else P(axis)
    S, B = args.seq, args.batch
    tokens = jax.device_put(
        jax.random.randint(key, (S, B), 0, cfg.vocab, jnp.int32),
        NamedSharding(mesh, batch_spec))
    targets = jnp.roll(tokens, -1, axis=0)

    start = 0
    mgr = None
    if args.ckpt_dir:
        mgr = CheckpointManager(args.ckpt_dir, max_to_keep=args.keep)
        try:
            resumed = mgr.restore_latest(like=state)
        except Exception:
            # Pre-optimizer checkpoints stored the bare param tree; wrap
            # them into the current {"params": ...} layout on restore.
            if args.opt != "sgd":
                raise
            resumed = mgr.restore_latest(like=state["params"])
            if resumed is not None:
                resumed = (resumed[0], {"params": resumed[1]})
        if resumed is not None:
            start, state = resumed[0] + 1, resumed[1]
            dist_print(f"resumed from step {resumed[0]}")

    hb_path = args.heartbeat or (
        os.path.join(args.ckpt_dir, f"heartbeat.{jax.process_index()}")
        if args.ckpt_dir else None)

    def loop():
        nonlocal state
        saved = start - 1
        for step in range(start, args.steps):
            t0 = time.perf_counter()
            state, loss = step_fn(state, tokens, targets)
            loss = block_until_ready_with_timeout(
                loss, args.step_timeout, name=f"train step {step}")
            dt = time.perf_counter() - t0
            dist_print(f"step {step:4d}  loss {float(loss):.4f}  "
                       f"{dt * 1e3:7.1f} ms")
            if mgr is not None and (step + 1) % args.ckpt_every == 0:
                mgr.save(step, state)
                saved = step
                dist_print(f"checkpointed step {step}")
        if mgr is not None and saved < args.steps - 1:
            mgr.save(args.steps - 1, state)

    import contextlib

    with contextlib.ExitStack() as stack:
        if hb_path:
            stack.enter_context(Heartbeat(hb_path, interval_s=10.0))
        if args.profile:
            stack.enter_context(group_profile("train",
                                              base_dir=args.profile))
        loop()
    dist_print("done")


if __name__ == "__main__":
    main()
