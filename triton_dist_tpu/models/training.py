"""Optimizer integration: optax train steps over the overlapped kernels.

The family modules (models/llama.py, models/moe.py) ship a plain-SGD
``make_train_step`` that fuses loss, backward, gradient psums, and the
update into one shard_map program.  Real training wants a stateful
optimizer (AdamW etc.); this module composes any optax ``GradientTransform``
with the families' gradient programs:

- ``make_grads`` — the shard_map program: loss + backward through the
  overlapped kernels' custom VJPs + the per-leaf gradient psums (the same
  reduction rules as the SGD steps: tp-sharded leaves are complete per
  shard, replicated leaves psum over tp, everything psums over dp).
- ``make_optax_train_step`` — wraps ``make_grads`` with ``tx.update`` under
  plain jit: the update is elementwise, so XLA propagates the parameter
  shardings onto the optimizer state (mu/nu mirror the param layout; no
  hand-written opt-state PartitionSpecs needed).

Optimizer state is a pytree of sharded jax.Arrays like params, so
``runtime.checkpoint`` saves/restores {params, opt_state, step} together.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _reduce_grads(grads, specs, axis, dp_axis):
    """The families' shared gradient-reduction rule (llama.py:301-315)."""

    def _reduce(g, spec):
        sharded_on_tp = any(s == axis for s in spec)
        axes = () if sharded_on_tp else (axis,)
        if dp_axis is not None:
            axes = axes + (dp_axis,)
        return jax.lax.psum(g, axes) if axes else g

    return jax.tree.map(_reduce, grads, specs,
                        is_leaf=lambda x: isinstance(x, P))


def make_grads(family, cfg, mesh: Mesh, *, axis="tp", dp_axis=None,
               impl="auto", interpret=False) -> tuple[Callable, Any]:
    """(grads_fn, specs): ``grads_fn(params, tokens, targets) -> (loss,
    grads)`` jitted over the mesh.  ``family`` is models.llama or
    models.moe (anything with ``loss_shard`` + ``param_specs``)."""
    specs = family.param_specs(cfg, axis)
    batch_spec = P(axis, dp_axis) if dp_axis else P(axis)

    def grads_shard(params, tokens, targets):
        local_loss, grads = jax.value_and_grad(family.loss_shard)(
            params, tokens, targets, cfg, axis=axis, dp_axis=dp_axis,
            impl=impl, interpret=interpret)
        all_axes = (axis,) if dp_axis is None else (axis, dp_axis)
        loss = jax.lax.psum(local_loss, all_axes)
        return loss, _reduce_grads(grads, specs, axis, dp_axis)

    fn = jax.shard_map(
        grads_shard, mesh=mesh,
        in_specs=(specs, batch_spec, batch_spec),
        out_specs=(P(), specs),
        check_vma=False)
    return jax.jit(fn), specs


def make_optax_train_step(family, cfg, mesh: Mesh, tx, *, axis="tp",
                          dp_axis=None, impl="auto", interpret=False):
    """(step, init): optax training over the overlapped kernels.

    ``init(params) -> opt_state`` (sharding follows params);
    ``step(params, opt_state, tokens, targets) -> (params, opt_state,
    loss)``.  ``tx`` is any ``optax.GradientTransformation``.
    """
    grads_fn, _specs = make_grads(family, cfg, mesh, axis=axis,
                                  dp_axis=dp_axis, impl=impl,
                                  interpret=interpret)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = grads_fn(params, tokens, targets)
        # Cast grads to param dtype for the update (families keep bf16
        # params; optax moments accumulate in the same dtype as given).
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return jax.tree.map(lambda p, u: p + u.astype(p.dtype),
                            params, updates), opt_state, loss

    def init(params):
        return shard_opt_state_like(tx.init(params), params)

    return step, init


def shard_opt_state_like(opt_state, params):
    """Place optimizer-state leaves in the matching parameters' shardings.

    ``tx.init`` builds moments with ``zeros_like``, which carries shape and
    dtype but no *value* dependence on the parameter — so jit's sharding
    propagation gives the zeros default (single-device) placement.  Optax
    states embed params-shaped subtrees at params-shaped keypaths (e.g.
    ``[0].mu['layers'][0]['wq']`` for param ``['layers'][0]['wq']``), so
    each state leaf takes the sharding of the param whose keypath is a
    suffix of its own; scalars and unmatched leaves replicate on the same
    devices.
    """
    p_leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    by_path = {tuple(str(k) for k in path): (leaf.sharding, leaf.shape)
               for path, leaf in p_leaves if isinstance(leaf, jax.Array)}
    some_sharding = next(iter(by_path.values()))[0]
    replicated = jax.sharding.NamedSharding(some_sharding.mesh, P())

    def place(path, leaf):
        keys = tuple(str(k) for k in path)
        for start in range(len(keys)):
            hit = by_path.get(keys[start:])
            if hit is not None and hit[1] == jnp.shape(leaf):
                return jax.device_put(leaf, hit[0])
        return jax.device_put(leaf, replicated)

    return jax.tree_util.tree_map_with_path(place, opt_state)
