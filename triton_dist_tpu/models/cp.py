"""Context-parallel (long-context) training mode for the Llama family.

The training-side long-context story (SURVEY.md §5: the reference scales
long sequences only at decode time, by sharding the KV cache).  Here the
*training* sequence is sharded across the ``cp`` mesh axis end-to-end:
activations stay ``[S_loc, B, D]`` through every block, weights are
replicated, and attention crosses the shards through either SP scheme:

* ``attn="ring"``   — KV blocks rotate the ring (kernels/ring_attention.py);
  memory-light, works for any head count.  Defaults to the ZIGZAG
  sequence layout (rank i holds chunks i and 2w-1-i) whenever
  S % (2*world) == 0 — the causal work balancer that halves ring step
  time (ring_attention.py module docstring); tokens/targets are
  permuted into zigzag order at the jit boundary and logits permuted
  back, so the public contract stays natural-order.
* ``attn="ulysses"`` — head-scatter AllToAll (kernels/ulysses_attention.py);
  communication independent of world size, needs heads % world == 0.

Composes with a ``dp`` axis the usual way (batch sharding + gradient
psum).  RoPE uses global positions (each shard offsets by its rank — the
zigzag shard offsets each of its two chunks), so the sharded model is
bit-for-bit the same function as the unsharded one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.ring_attention import (
    from_zigzag,
    ring_attention_shard,
    to_zigzag,
)
from triton_dist_tpu.kernels.ulysses_attention import ulysses_attention_shard
from triton_dist_tpu.models.llama import (
    LlamaConfig,
    _rms_norm,
    _rope,
    init_params,
    param_specs as _tp_param_specs,
)


def cp_param_specs(cfg: LlamaConfig) -> dict:
    """All weights replicated (pure CP; the sharded thing is the sequence)."""
    return jax.tree.map(lambda _: P(), _tp_param_specs(cfg),
                        is_leaf=lambda x: isinstance(x, P))


def _cp_attention_block(x, layer, cfg: LlamaConfig, *, axis, attn, impl,
                        interpret, zigzag=False):
    """Attention with sequence-sharded activations and replicated weights."""
    s_loc, b, _ = x.shape
    me = jax.lax.axis_index(axis)
    hd = cfg.head_dim
    if zigzag:
        # Shard = chunks (me, 2w-1-me): RoPE positions follow the layout.
        c = s_loc // 2
        world = jax.lax.axis_size(axis)
        base = jnp.arange(c, dtype=jnp.int32)
        positions = jnp.concatenate(
            [me * c + base, (2 * world - 1 - me) * c + base])
    else:
        positions = me * s_loc + jnp.arange(s_loc, dtype=jnp.int32)

    h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    h2 = h.reshape(s_loc * b, cfg.dim)
    q = (h2 @ layer["wq"]).reshape(s_loc, b, cfg.n_heads, hd)
    k = (h2 @ layer["wk"]).reshape(s_loc, b, cfg.n_kv_heads, hd)
    v = (h2 @ layer["wv"]).reshape(s_loc, b, cfg.n_kv_heads, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    if attn == "ring":
        o = ring_attention_shard(q, k, v, axis=axis, causal=True, impl=impl,
                                 interpret=interpret, window=cfg.attn_window,
                                 soft_cap=cfg.attn_soft_cap, zigzag=zigzag)
    else:
        assert not zigzag, "zigzag layout applies to attn='ring' only"
        o = ulysses_attention_shard(q, k, v, axis=axis, causal=True,
                                    impl=impl, interpret=interpret,
                                    window=cfg.attn_window,
                                    soft_cap=cfg.attn_soft_cap)
    o2 = o.reshape(s_loc * b, cfg.n_heads * hd)
    return x + (o2 @ layer["wo"]).reshape(s_loc, b, cfg.dim)


def _cp_layer(x, layer, cfg: LlamaConfig, *, axis, attn, impl, interpret,
              zigzag=False):
    """One decoder layer (SP attention + local MLP) on x [S_loc, B, D]."""
    s_loc, b, _ = x.shape
    x = _cp_attention_block(x, layer, cfg, axis=axis, attn=attn,
                            impl=impl, interpret=interpret, zigzag=zigzag)
    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    h2 = h.reshape(s_loc * b, cfg.dim)
    act = (jax.nn.silu((h2 @ layer["wgate"]).astype(jnp.float32))
           .astype(x.dtype) * (h2 @ layer["wup"]))
    return x + (act @ layer["wdown"]).reshape(s_loc, b, cfg.dim)


def cp_forward_shard(params, tokens_shard, cfg: LlamaConfig, *, axis,
                     attn="ring", impl="auto", interpret=False,
                     remat=False, zigzag=False):
    """tokens_shard [S_loc, B] (sequence sharded; zigzag chunk order when
    ``zigzag``).  Local MLP, SP attention.

    ``remat=True`` wraps each layer in ``jax.checkpoint``: the backward
    pass recomputes the layer (including its ring/Ulysses communication)
    instead of stashing activations — the standard memory/FLOPs trade for
    long-context training, where per-layer activations dominate HBM."""
    layer_fn = functools.partial(_cp_layer, cfg=cfg, axis=axis, attn=attn,
                                 impl=impl, interpret=interpret,
                                 zigzag=zigzag)
    if remat:
        layer_fn = jax.checkpoint(layer_fn)
    x = params["embed"][tokens_shard]
    for layer in params["layers"]:
        x = layer_fn(x, layer)
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return jnp.dot(x, params["lm_head"], preferred_element_type=jnp.float32)


def _pick_zigzag(zigzag, attn, S, world, impl, head_dim):
    """Auto rule (``zigzag=None``): zigzag only where it PAYS — the flash
    ring's block-level skip prunes the dead chunk-pairs; the dense
    xla/pallas updates compute full blocks regardless of mask, and a
    zigzag run must tile by 128 for the flash kernels (S_loc % 256).
    Flash-illegal or explicitly-xla configs keep the contiguous layout
    (zigzag would force them OFF the flash ring).  world 1 gains nothing.
    Explicit ``zigzag=True`` is validated here (a ValueError, not a
    traced assert) and overrides the pay-off heuristic."""
    if zigzag is None:
        return (attn == "ring" and world > 1 and S % (2 * world) == 0
                and impl in ("auto", "flash")
                and (S // world) % 256 == 0 and head_dim % 128 == 0)
    if zigzag:
        if attn != "ring":
            raise ValueError("zigzag layout applies to attn='ring' only "
                             f"(got attn={attn!r})")
        if S % (2 * world):
            raise ValueError(f"zigzag needs S % (2*world) == 0, got "
                             f"S={S}, world={world}")
    return bool(zigzag)


def make_cp_train_step(cfg: LlamaConfig, mesh: Mesh, *, axis="cp",
                       dp_axis=None, attn="ring", impl="auto",
                       interpret=False, lr=1e-3, remat=False, zigzag=None):
    """SGD step for the CP mode.  Gradients: every leaf is replicated, so
    psum over the cp axis (each shard saw only its sequence chunk) and dp.

    ``zigzag`` (default auto): ring CP uses the load-balanced zigzag
    sequence layout; tokens/targets are permuted at the jit boundary
    (cross-entropy is permutation-invariant, so the loss and gradients
    are bit-for-bit those of the natural order)."""
    specs = cp_param_specs(cfg)
    batch_spec = P(axis, dp_axis) if dp_axis else P(axis)
    all_axes = (axis,) if dp_axis is None else (axis, dp_axis)
    world = mesh.shape[axis]

    def build(zz):
        def loss_shard(params, tokens, targets):
            logits = cp_forward_shard(params, tokens, cfg, axis=axis,
                                      attn=attn, impl=impl,
                                      interpret=interpret, remat=remat,
                                      zigzag=zz)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(
                logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
            denom = ll.size * jax.lax.axis_size(axis)
            if dp_axis is not None:
                denom = denom * jax.lax.axis_size(dp_axis)
            return -jnp.sum(ll) / denom

        def step_shard(params, tokens, targets):
            local_loss, grads = jax.value_and_grad(loss_shard)(
                params, tokens, targets)
            loss = jax.lax.psum(local_loss, all_axes)
            grads = jax.tree.map(lambda g: jax.lax.psum(g, all_axes), grads)
            new_params = jax.tree.map(
                lambda p, g: p - lr * g.astype(p.dtype), params, grads)
            return new_params, loss

        return jax.shard_map(
            step_shard, mesh=mesh,
            in_specs=(specs, batch_spec, batch_spec),
            out_specs=(specs, P()),
            check_vma=False,
        )

    fns = {}

    def step(params, tokens, targets):
        zz = _pick_zigzag(zigzag, attn, tokens.shape[0], world,
                          impl, cfg.head_dim)
        if zz not in fns:
            fns[zz] = build(zz)
        if zz:
            tokens = to_zigzag(tokens, world)
            targets = to_zigzag(targets, world)
        return fns[zz](params, tokens, targets)

    return jax.jit(step), specs


def make_cp_forward(cfg: LlamaConfig, mesh: Mesh, *, axis="cp", attn="ring",
                    impl="auto", interpret=False, zigzag=None):
    """Full-sequence logits in NATURAL order (any zigzag permutation is
    applied to tokens and inverted on the logits inside the jit)."""
    specs = cp_param_specs(cfg)
    world = mesh.shape[axis]

    def build(zz):
        return jax.shard_map(
            functools.partial(cp_forward_shard, cfg=cfg, axis=axis,
                              attn=attn, impl=impl, interpret=interpret,
                              zigzag=zz),
            mesh=mesh, in_specs=(specs, P(axis)), out_specs=P(axis),
            check_vma=False,
        )

    fns = {}

    def fwd(params, tokens):
        zz = _pick_zigzag(zigzag, attn, tokens.shape[0], world,
                          impl, cfg.head_dim)
        if zz not in fns:
            fns[zz] = build(zz)
        if not zz:
            return fns[zz](params, tokens)
        return from_zigzag(fns[zz](params, to_zigzag(tokens, world)), world)

    return jax.jit(fwd)


def place_cp_params(params, cfg: LlamaConfig, mesh: Mesh) -> dict:
    specs = cp_param_specs(cfg)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)
