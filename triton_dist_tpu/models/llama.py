"""Llama-style dense transformer, TPU-native and kernel-wired.

The flagship model: every TP linear in the network runs through the
overlapped AG-GEMM / GEMM-RS Pallas kernels (sequence-parallel Megatron
layout), forward and backward, under one ``shard_map``.

Reference analog: the reference's model surface is its LLaMA-shape kernel
test configs (``test/nvidia/test_ag_gemm.py --shape_id LLaMA-3.1-70B`` etc.)
plus inference layers; it has no trainer.  Here the same shapes run as an
actual model with a training step — the capability the kernels exist for.

Layout conventions (Megatron sequence-parallel, seq-major):

* Activations between blocks: ``[S_loc, B, D]`` — sequence sharded over the
  ``tp`` axis, batch sharded over ``dp``.
* QKV / up / gate projections: column-parallel (AG over sequence fused with
  the GEMM); attention and the MLP nonlinearity run on full sequence with
  local heads / local FFN columns; out / down projections: row-parallel
  (GEMM fused with RS back to sequence-sharded).
* GQA attention with RoPE; RMSNorm; SwiGLU — the Llama-3 recipe.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.layers.tp_linear import (
    column_parallel_linear,
    row_parallel_linear,
)


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 32000
    dim: int = 512
    n_layers: int = 2
    n_heads: int = 8
    n_kv_heads: int = 4
    ffn_dim: int = 1408
    max_seq: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: object = jnp.float32
    # Attention variants (r4): sliding window (Mistral) and logit
    # soft-capping (Gemma-2), threaded to the flash kernels by every
    # model path.  0 = off.
    attn_window: int = 0
    attn_soft_cap: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        """The reference's benchmark shape (test_ag_gemm.py LLaMA-3.1-70B)."""
        return LlamaConfig(vocab=128256, dim=8192, n_layers=80, n_heads=64,
                           n_kv_heads=8, ffn_dim=28672, dtype=jnp.bfloat16)

    # Presets mirroring the rest of the reference's --shape_id table
    # (test_ag_gemm.py:149-154): K = dim, N = ffn_dim.

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(vocab=128256, dim=4096, n_layers=32, n_heads=32,
                           n_kv_heads=8, ffn_dim=14336, dtype=jnp.bfloat16)

    @staticmethod
    def llama3_405b() -> "LlamaConfig":
        return LlamaConfig(vocab=128256, dim=16384, n_layers=126,
                           n_heads=128, n_kv_heads=8, ffn_dim=53248,
                           dtype=jnp.bfloat16)

    @staticmethod
    def mistral_7b() -> "LlamaConfig":
        # NOTE: the presets mirror the reference's GEMM-shape table, so
        # attention variants stay off by default; Mistral's real sliding
        # window is ``replace(cfg, attn_window=4096)`` — windowed
        # prefill, training, and (since r5) SP decode work on any mesh.
        return LlamaConfig(vocab=32000, dim=4096, n_layers=32, n_heads=32,
                           n_kv_heads=8, ffn_dim=14336, rope_theta=1e6,
                           dtype=jnp.bfloat16)

    @staticmethod
    def qwen2_72b() -> "LlamaConfig":
        return LlamaConfig(vocab=152064, dim=8192, n_layers=80, n_heads=64,
                           n_kv_heads=8, ffn_dim=29568, rope_theta=1e6,
                           dtype=jnp.bfloat16)

    @staticmethod
    def tiny(dtype=jnp.float32) -> "LlamaConfig":
        """CPU-mesh test size; every PER-SHARD dim on a tp=4 mesh still
        tiles the MXU legally (n%128, k%128 of the shard — the strict
        impl='pallas' gate enforces it): kv-proj N = n_kv_heads*head_dim
        = 512 and o-proj K = dim = 1024 both leave 128+ per device."""
        return LlamaConfig(vocab=512, dim=1024, n_layers=2, n_heads=8,
                           n_kv_heads=4, ffn_dim=1024, max_seq=256,
                           dtype=dtype)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def init_params(cfg: LlamaConfig, key) -> dict:
    """Parameter pytree.  TP-sharded matrices carry their full (unsharded)
    shapes; ``param_specs`` says how each leaf is laid out on the mesh."""
    hd = cfg.head_dim
    qkv_out = (cfg.n_heads + 2 * cfg.n_kv_heads) * hd

    def dense(k, fan_in, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(cfg.dtype)

    del qkv_out
    keys = jax.random.split(key, 2 + cfg.n_layers)
    params = {
        "embed": dense(keys[0], 1, (cfg.vocab, cfg.dim)),
        "lm_head": dense(keys[1], cfg.dim, (cfg.dim, cfg.vocab)),
        "final_norm": jnp.ones((cfg.dim,), cfg.dtype),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[2 + i], 7)
        # Q/K/V are separate column-sharded matrices (head-major columns, so
        # a contiguous tp split assigns whole heads per device); the forward
        # concatenates the *local* shards and runs ONE fused AG-GEMM.
        params["layers"].append({
            "attn_norm": jnp.ones((cfg.dim,), cfg.dtype),
            "mlp_norm": jnp.ones((cfg.dim,), cfg.dtype),
            "wq": dense(lk[0], cfg.dim, (cfg.dim, cfg.n_heads * hd)),
            "wk": dense(lk[5], cfg.dim, (cfg.dim, cfg.n_kv_heads * hd)),
            "wv": dense(lk[2], cfg.dim, (cfg.dim, cfg.n_kv_heads * hd)),
            "wo": dense(lk[1], cfg.n_heads * hd, (cfg.n_heads * hd, cfg.dim)),
            "wgate": dense(lk[3], cfg.dim, (cfg.dim, cfg.ffn_dim)),
            "wup": dense(lk[4], cfg.dim, (cfg.dim, cfg.ffn_dim)),
            "wdown": dense(lk[6], cfg.ffn_dim, (cfg.ffn_dim, cfg.dim)),
        })
    return params


def param_specs(cfg: LlamaConfig, axis: str = "tp") -> dict:
    """PartitionSpec tree matching :func:`init_params` (sharded over the
    tensor-parallel ``axis`` only; replicate over dp)."""
    layer = {
        "attn_norm": P(), "mlp_norm": P(),
        "wq": P(None, axis),       # column-parallel (whole heads per device)
        "wk": P(None, axis),
        "wv": P(None, axis),
        "wo": P(axis, None),       # row-parallel
        "wgate": P(None, axis),
        "wup": P(None, axis),
        "wdown": P(axis, None),
    }
    return {
        "embed": P(), "lm_head": P(), "final_norm": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


# ---------------------------------------------------------------------------
# Shard-level forward (call inside shard_map)
# ---------------------------------------------------------------------------


def _rms_norm(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _rope(x, positions, theta):
    """x: [S, B, H, hd]; rotate pairs (Llama convention)."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (np.arange(0, hd, 2, dtype=np.float32) / hd))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, hd/2]
    cos = jnp.cos(angles)[:, None, None, :]
    sin = jnp.sin(angles)[:, None, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig, *, impl="auto", interpret=False):
    """Causal GQA attention on local heads.  q: [S, B, Hq_loc, hd],
    k/v: [S, B, Hkv_loc, hd].  Full sequence, local heads (TP over heads).

    Routed through the flash prefill kernel (O(S) memory, blockwise
    online softmax) whenever shapes allow; the dense path only remains
    for ragged shapes / head_dim < 128.  The model-level ``impl``
    contract is about the collective kernels, so anything but an explicit
    ``"xla"`` leaves attention dispatch at ``"auto"`` (flash's strict
    mode is exercised by its own tests — tests/test_flash_attention.py)."""
    from triton_dist_tpu.kernels.flash_attention import flash_gqa_attention

    return flash_gqa_attention(q, k, v, causal=True,
                               scale=1.0 / math.sqrt(cfg.head_dim),
                               impl="xla" if impl == "xla" else "auto",
                               interpret=interpret,
                               window=cfg.attn_window,
                               soft_cap=cfg.attn_soft_cap)


def attention_block_shard(x, layer, cfg: LlamaConfig, *, axis, impl,
                          interpret):
    """Sequence-parallel TP attention sub-block shared by the model families
    (Llama dense, MoE): RMSNorm → fused-QKV column-parallel AG-GEMM → RoPE →
    causal GQA on local heads → row-parallel GEMM-RS, residual added.
    x: [S_loc, B, D].  ``layer`` needs attn_norm/wq/wk/wv/wo shards."""
    world = jax.lax.axis_size(axis)
    s_loc, b, _ = x.shape
    hd = cfg.head_dim
    hq_loc = cfg.n_heads // world
    hkv_loc = cfg.n_kv_heads // world
    full_positions = jnp.arange(world * s_loc, dtype=jnp.int32)
    lin_c = functools.partial(column_parallel_linear, axis=axis, impl=impl,
                              interpret=interpret)
    lin_r = functools.partial(row_parallel_linear, axis=axis, impl=impl,
                              interpret=interpret)

    h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    # Local Q/K/V column shards concatenate into one fused weight so the
    # sequence-allgather happens once per block.
    wqkv = jnp.concatenate([layer["wq"], layer["wk"], layer["wv"]], axis=1)
    qkv = lin_c(h.reshape(s_loc * b, cfg.dim), wqkv)
    qkv = qkv.reshape(world * s_loc, b, (hq_loc + 2 * hkv_loc) * hd)
    q, k, v = jnp.split(
        qkv, [hq_loc * hd, (hq_loc + hkv_loc) * hd], axis=-1)
    q = _rope(q.reshape(-1, b, hq_loc, hd), full_positions, cfg.rope_theta)
    k = _rope(k.reshape(-1, b, hkv_loc, hd), full_positions, cfg.rope_theta)
    v = v.reshape(-1, b, hkv_loc, hd)
    o = _attention(q, k, v, cfg, impl=impl,
                   interpret=interpret)  # [S, B, Hq_loc, hd]
    o = o.reshape(world * s_loc * b, hq_loc * hd)
    return x + lin_r(o, layer["wo"]).reshape(s_loc, b, cfg.dim)


def mlp_block_shard(x, layer, cfg: LlamaConfig, *, axis, impl, interpret):
    """SwiGLU MLP sub-block (sequence-parallel residual): RMSNorm →
    column-parallel gate/up AG-GEMMs → silu·mul → row-parallel down
    GEMM-RS, residual added.  x: [S_loc, B, D]."""
    s_loc, b, _ = x.shape
    lin_c = functools.partial(column_parallel_linear, axis=axis, impl=impl,
                              interpret=interpret)
    lin_r = functools.partial(row_parallel_linear, axis=axis, impl=impl,
                              interpret=interpret)
    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    h2 = h.reshape(s_loc * b, cfg.dim)
    gate = lin_c(h2, layer["wgate"])
    up = lin_c(h2, layer["wup"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    return x + lin_r(act, layer["wdown"]).reshape(s_loc, b, cfg.dim)


def forward_shard(params, tokens_shard, cfg: LlamaConfig, *, axis="tp",
                  impl="auto", interpret=False):
    """Per-device forward.  tokens_shard: [S_loc, B_loc] int32 (seq-major,
    sequence sharded over ``axis``).  Returns logits [S_loc, B_loc, vocab].

    Every projection is an overlapped distributed GEMM; weight shards arrive
    pre-sliced by shard_map according to :func:`param_specs`.
    """
    world = jax.lax.axis_size(axis)
    assert cfg.n_heads % world == 0 and cfg.n_kv_heads % world == 0, (
        f"TP over {world} devices needs n_heads ({cfg.n_heads}) and "
        f"n_kv_heads ({cfg.n_kv_heads}) divisible by it")

    x = params["embed"][tokens_shard]  # [S_loc, B, D]

    for layer in params["layers"]:
        x = attention_block_shard(x, layer, cfg, axis=axis, impl=impl,
                                  interpret=interpret)
        x = mlp_block_shard(x, layer, cfg, axis=axis, impl=impl,
                            interpret=interpret)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    # Vocab projection: local tokens x replicated lm_head (seq stays sharded).
    return jnp.dot(x, params["lm_head"],
                   preferred_element_type=jnp.float32)


def loss_shard(params, tokens_shard, targets_shard, cfg: LlamaConfig, *,
               axis="tp", dp_axis=None, impl="auto", interpret=False):
    """Per-device *contribution* to the global mean next-token CE loss
    (``psum`` of this over all devices == the global mean).

    Deliberately local: autodiff must NOT pass through a ``psum`` — under
    ``shard_map(check_vma=False)`` its transpose over-counts by the axis
    size.  Cross-device gradient flow for the TP weights happens correctly
    through the AG↔RS duality of the custom VJPs in ``tp_linear``; grads of
    locally-used replicated leaves (embed/lm_head/norms) are psum'd by the
    train step."""
    logits = forward_shard(params, tokens_shard, cfg, axis=axis, impl=impl,
                           interpret=interpret)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets_shard[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    denom = ll.size * jax.lax.axis_size(axis)
    if dp_axis is not None:
        denom = denom * jax.lax.axis_size(dp_axis)
    return -jnp.sum(ll) / denom


# ---------------------------------------------------------------------------
# Host-level entries
# ---------------------------------------------------------------------------


def make_forward(cfg: LlamaConfig, mesh: Mesh, *, axis="tp", dp_axis=None,
                 impl="auto", interpret=False):
    """jit(shard_map(forward)) over the mesh.  Input tokens: [S, B] int32."""
    batch_spec = P(axis, dp_axis) if dp_axis else P(axis)
    specs = param_specs(cfg)

    fn = jax.shard_map(
        functools.partial(forward_shard, cfg=cfg, axis=axis, impl=impl,
                          interpret=interpret),
        mesh=mesh,
        in_specs=(specs, batch_spec),
        out_specs=P(axis, dp_axis) if dp_axis else P(axis),
        check_vma=False,
    )
    return jax.jit(fn)


def make_train_step(cfg: LlamaConfig, mesh: Mesh, *, axis="tp", dp_axis=None,
                    impl="auto", interpret=False, lr=1e-3):
    """Full SGD training step: loss, grads through the overlapped kernels
    (custom VJPs), psum over dp, parameter update.  Returns (step, specs).

    The multi-chip training story the driver dry-runs
    (``__graft_entry__.dryrun_multichip``)."""
    specs = param_specs(cfg)
    batch_spec = P(axis, dp_axis) if dp_axis else P(axis)

    def step_shard(params, tokens, targets):
        local_loss, grads = jax.value_and_grad(loss_shard)(
            params, tokens, targets, cfg, axis=axis, dp_axis=dp_axis,
            impl=impl, interpret=interpret)
        all_axes = (axis,) if dp_axis is None else (axis, dp_axis)
        loss = jax.lax.psum(local_loss, all_axes)  # reported, not diff'd

        # Gradient reductions: each device holds only its local contribution
        # for leaves it shares with other devices.  Replicated leaves (embed,
        # lm_head, norms) need a psum over tp (each tp device saw only its
        # sequence chunk) and dp; tp-sharded weight grads are complete per
        # shard (the custom VJPs gather the full-sequence cotangent) but
        # still need summing over dp batches.
        def _reduce(g, spec):
            sharded_on_tp = any(s == axis for s in spec)
            axes = () if sharded_on_tp else (axis,)
            if dp_axis is not None:
                axes = axes + (dp_axis,)
            return jax.lax.psum(g, axes) if axes else g

        grads = jax.tree.map(_reduce, grads, specs,
                             is_leaf=lambda x: isinstance(x, P))
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        return new_params, loss

    fn = jax.shard_map(
        step_shard,
        mesh=mesh,
        in_specs=(specs, batch_spec, batch_spec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(fn), specs


def place_params(params, cfg: LlamaConfig, mesh: Mesh) -> dict:
    """Device-put a host param tree according to ``param_specs``."""
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)
