"""Autoregressive generation over the sequence-parallel KV cache.

The serving-side capability the reference's decode stack exists for, taken
end-to-end: prefill writes per-layer K/V into sequence-sharded caches, and
every decode step runs the SP flash-decode path — local split-KV partials
on each rank's shard, low-latency allgather, LSE combine
(layers/sp_flash_decode.py; reference sp_flash_decode_layer.py:43-184 has
the attention module but no model or loop around it).

Weights are replicated (the decode-serving layout: the sharded thing is
the KV cache); works on any mesh axis, including world 1.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from triton_dist_tpu.layers.sp_flash_decode import SpGQAFlashDecodeAttention
from triton_dist_tpu.models.llama import LlamaConfig, _rms_norm, _rope


@dataclass
class GenerationState:
    """Per-layer sharded KV caches + global lengths."""

    caches: list  # [(k_cache, v_cache)] per layer, [B, Hkv, S, D] sharded
    kv_lens: jax.Array  # [B] int32 — tokens currently in the cache
    last_logits: jax.Array  # [B, vocab] f32 — logits for the next token


def _rope_at(x, pos, theta):
    """RoPE for single-position vectors.  x [B, H, hd]; pos [B] int32."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (np.arange(0, hd, 2, dtype=np.float32) / hd))
    angles = pos[:, None].astype(jnp.float32) * freqs[None, :]  # [B, hd/2]
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class Generator:
    """Greedy autoregressive decoder for the Llama family.

    Usage::

        gen = Generator(cfg, mesh, axis="sp", max_seq=4096)
        state = gen.prefill(params, prompt_tokens)       # [B, S0]
        tokens, state = gen.generate(params, state, n_new=64)
    """

    def __init__(self, cfg: LlamaConfig, mesh: Mesh, *, axis: str = "sp",
                 max_seq: int | None = None, impl: str = "auto",
                 interpret: bool = False, kv_dtype=None):
        self.cfg = cfg
        self.mesh = mesh
        self.axis = axis
        self.max_seq = max_seq or cfg.max_seq
        self.attn = SpGQAFlashDecodeAttention(
            mesh, axis=axis, impl=impl, interpret=interpret,
            check_bounds=False,  # Generator guards lengths itself (below)
            kv_dtype=kv_dtype,   # jnp.int8 = quantized KV cache
            soft_cap=cfg.attn_soft_cap, window=cfg.attn_window)
        self._prefill_jit = jax.jit(functools.partial(
            _prompt_forward, cfg=cfg, impl=impl, interpret=interpret))
        # caches are donated: each chunk's dynamic-update happens in place
        # instead of copying every layer's full-size cache per chunk.
        # Chunk attention at world > 1 enters shard_map over the
        # sequence-SHARDED cache (per-shard flash + LSE combine, the
        # decode SP recipe on prefill) — mesh/axis carry the topology in.
        self._chunk_jit = jax.jit(
            functools.partial(_chunk_forward, cfg=cfg, impl=impl,
                              interpret=interpret, mesh=mesh, axis=axis),
            static_argnames=("quantized", "extent"),
            donate_argnums=(2,))
        # Batched speculative-verify pass (r5): per-row cache lengths
        # through the multi-token decode kernel; cached here so serving
        # loops don't recompile per generate() call.  MoEGenerator
        # rebuilds it with its ffn hook.
        self._verify_jit = jax.jit(
            functools.partial(_verify_forward, cfg=cfg, impl=impl,
                              interpret=interpret),
            donate_argnums=(2,))
        self._step_jit = jax.jit(self._step_impl)
        # generate_onchip programs, keyed by (n_new, sampled, knobs) —
        # one compiled scan per distinct call signature.
        self._onchip_cache: dict = {}

    # -- prefill ----------------------------------------------------------

    def prefill(self, params, tokens) -> GenerationState:
        """Run the prompt [B, S0], fill the caches, return the state."""
        cfg = self.cfg
        B, S0 = tokens.shape
        if S0 > self.max_seq:
            raise ValueError(f"prompt length {S0} > max_seq {self.max_seq}")
        kvs, logits = self._prefill_jit(params, tokens)
        lens = jnp.full((B,), S0, jnp.int32)
        caches = []
        for (k_new, v_new) in kvs:  # [B, Hkv, S0, hd] each
            caches.append(self.attn.init_cache(
                B, cfg.n_kv_heads, self.max_seq, cfg.head_dim,
                dtype=cfg.dtype, k_init=k_new, v_init=v_new))
        return GenerationState(caches=caches, kv_lens=lens,
                               last_logits=logits[:, -1])

    def prefill_chunked(self, params, tokens,
                        chunk_size: int = 512) -> GenerationState:
        """Prefill in fixed-size chunks against the growing KV cache.

        Activation memory is bounded by the chunk (scores are [c, S]
        instead of the one-shot prefill's [S0, S0]); each chunk's K/V
        lands in the cache (quantized for int8 caches) and later chunks
        attend to it.  Same final state as :meth:`prefill` up to KV-cache
        quantization of earlier chunks.
        """
        cfg = self.cfg
        B, S0 = tokens.shape
        if S0 > self.max_seq:
            raise ValueError(f"prompt length {S0} > max_seq {self.max_seq}")
        caches = [self.attn.init_cache(B, cfg.n_kv_heads, self.max_seq,
                                       cfg.head_dim, dtype=cfg.dtype)
                  for _ in range(cfg.n_layers)]
        logits = None
        # Attention only needs cache rows [0, S0); slicing to a fixed
        # extent keeps scores at [chunk, ~S0] instead of [chunk, max_seq]
        # (one trace per extent — constant across this prefill's chunks).
        extent = min(self.max_seq,
                     -(-S0 // chunk_size) * chunk_size)
        for off in range(0, S0, chunk_size):
            chunk = tokens[:, off:off + chunk_size]
            caches, logits = self._chunk_jit(
                params, chunk, caches, jnp.int32(off),
                quantized=self.attn.quantized, extent=extent)
        return GenerationState(caches=caches,
                               kv_lens=jnp.full((B,), S0, jnp.int32),
                               last_logits=logits[:, -1])

    # -- decode -----------------------------------------------------------

    def step(self, params, state: GenerationState, token,
             active=None) -> GenerationState:
        """One decode step: token [B] int32 → next state.

        ``active`` [B] bool (optional, r5): rows with ``active[b] ==
        False`` are FROZEN — their cache length does not advance (the
        dummy K/V write lands in the dead slot at ``kv_lens[b]``, masked
        by length; at ``kv_lens[b] == max_seq`` the owner check makes it
        a no-op).  The batched speculative loop retires finished rows
        this way so lockstep rounds cannot overflow a tightly
        provisioned cache.

        Raises on cache overflow when lengths are concrete (a dropped
        append would silently leave attention reading stale zero rows);
        jit-traced callers must bound steps themselves (``generate`` does).
        """
        if not isinstance(state.kv_lens, jax.core.Tracer):
            lens = state.kv_lens
            if active is not None:
                lens = jnp.where(active, lens, -1)  # frozen rows exempt
            top = int(jnp.max(lens))
            if top >= self.max_seq:
                raise ValueError(
                    f"KV cache overflow: decode at position {top} but "
                    f"max_seq={self.max_seq}")
        new_caches, kv_lens, logits = self._step_jit(
            params, state.caches, state.kv_lens, token, active)
        return GenerationState(caches=new_caches, kv_lens=kv_lens,
                               last_logits=logits)

    def _ffn_decode(self, h, layer):
        """Decode-step FFN hook: ``h`` [B, D] -> [B, D].  MoEGenerator
        overrides with the EP masked-expert path."""
        return _dense_prompt_ffn(h, layer)

    def _step_impl(self, params, caches, kv_lens, token, active=None):
        inc = (jnp.ones_like(kv_lens) if active is None
               else active.astype(kv_lens.dtype))

        def write_kv(li, cache, k, v):
            k_c, v_c = cache
            return self.attn.append_kv(k_c, v_c, k, v, kv_lens)

        def attend(li, q, cache):
            return self.attn(q, cache[0], cache[1], kv_lens + inc)

        new_caches, logits = _token_forward(
            params, caches, token, kv_lens, cfg=self.cfg,
            write_kv=write_kv, attend=attend, ffn=self._ffn_decode)
        return new_caches, kv_lens + inc, logits

    def generate(self, params, state: GenerationState, n_new: int,
                 sample=None, key=None, eos_id: int | None = None):
        """Generate up to ``n_new`` tokens.  Returns (tokens [B, n_new],
        state).

        Token choice per step:
        - default: greedy argmax;
        - ``key``: stochastic sampling — ``sample(logits, subkey)`` with a
          fresh subkey per step (``sample`` defaults to
          :func:`models.sampling.sample_logits`; pass
          ``sampling.make_sampler(temperature=..., top_k=..., top_p=...)``
          for the serving knobs);
        - ``sample`` without ``key``: deterministic ``sample(logits)``.

        ``eos_id``: rows that emit it keep emitting ``eos_id`` for the
        rest of the call (their caches still advance — batch rows stay in
        lockstep); the loop exits early once every row has finished.
        """
        if not isinstance(state.kv_lens, jax.core.Tracer):
            top = int(jnp.max(state.kv_lens))
            if top + n_new > self.max_seq:
                raise ValueError(
                    f"generate({n_new}) from position {top} would overflow "
                    f"max_seq={self.max_seq}")
        if key is not None and sample is None:
            from triton_dist_tpu.models.sampling import sample_logits
            sample = sample_logits
        outs = []
        done = None
        for _ in range(n_new):
            if key is not None:
                key, sub = jax.random.split(key)
                token = sample(state.last_logits, sub)
            elif sample is not None:
                token = sample(state.last_logits)
            else:
                token = jnp.argmax(state.last_logits, axis=-1).astype(
                    jnp.int32)
            if eos_id is not None:
                if done is None:
                    done = jnp.zeros(token.shape, bool)
                token = jnp.where(done, jnp.int32(eos_id), token)
                done = done | (token == eos_id)
            state = self.step(params, state, token)
            outs.append(token)
            if eos_id is not None and bool(jnp.all(done)):
                break
        tokens = jnp.stack(outs, axis=1)
        if eos_id is not None and tokens.shape[1] < n_new:
            pad = jnp.full((tokens.shape[0], n_new - tokens.shape[1]),
                           eos_id, jnp.int32)
            tokens = jnp.concatenate([tokens, pad], axis=1)
        return tokens, state

    def generate_onchip(self, params, state: GenerationState, n_new: int,
                        *, temperature: float = 1.0,
                        top_k: int | None = None,
                        top_p: float | None = None, key=None,
                        eos_id: int | None = None):
        """Device-resident decode: all ``n_new`` steps run as ONE traced
        ``lax.scan`` with on-device token choice — the host dispatches
        once and fetches a ``[B, n_new]`` buffer, instead of paying a
        dispatch + logits sync + host argmax/sample round trip per token
        (:meth:`generate`'s loop).  This is the single-model form of the
        serving engine's decode horizon (docs/serving.md).

        Emitted tokens are IDENTICAL to :meth:`generate` with the same
        arguments: greedy (no ``key``) is per-step argmax; with ``key``
        the scan splits it per step and draws through
        ``sampling.sample_logits`` exactly like the host loop, so the
        stream matches token for token — the sampler knobs default to
        ``sample_logits``'s own defaults (temperature 1.0), matching
        ``generate(key=k)``'s default sampler, and apply only when
        ``key`` is given.  ``eos_id`` rows keep emitting
        ``eos_id`` once they hit it — but the scan cannot break early, so
        the returned state always reflects ``n_new`` steps (the host loop
        stops stepping once every row is done; only the post-done cache
        tail differs, never a token)."""
        if not isinstance(state.kv_lens, jax.core.Tracer):
            top = int(jnp.max(state.kv_lens))
            if top + n_new > self.max_seq:
                raise ValueError(
                    f"generate_onchip({n_new}) from position {top} would "
                    f"overflow max_seq={self.max_seq}")
        sampled = key is not None
        sig = (int(n_new), sampled, float(temperature), top_k, top_p)
        fn = self._onchip_cache.get(sig)
        if fn is None:
            fn = self._build_onchip(int(n_new), sampled,
                                    float(temperature), top_k, top_p)
            self._onchip_cache[sig] = fn
        if key is None:
            key = jax.random.key(0)  # untraced-by-choice: greedy ignores it
        caches, kv_lens, logits, toks = fn(
            params, state.caches, state.kv_lens, state.last_logits, key,
            jnp.int32(-1 if eos_id is None else eos_id))
        return toks, GenerationState(caches=caches, kv_lens=kv_lens,
                                     last_logits=logits)

    def _build_onchip(self, n_new, sampled, temperature, top_k, top_p):
        from triton_dist_tpu.models.sampling import sample_logits

        def run(params, caches, kv_lens, last_logits, key, eos):
            has_eos = eos >= 0

            def step(carry, _):
                caches, kv_lens, logits, key, done = carry
                if sampled:
                    key, sub = jax.random.split(key)
                    token = sample_logits(logits, sub,
                                          temperature=temperature,
                                          top_k=top_k, top_p=top_p)
                else:
                    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                token = jnp.where(done, eos, token)
                done = done | (has_eos & (token == eos))
                caches, kv_lens, logits = self._step_impl(
                    params, caches, kv_lens, token, None)
                return (caches, kv_lens, logits, key, done), token

            done0 = jnp.zeros(kv_lens.shape, bool)
            (caches, kv_lens, logits, _, _), toks = jax.lax.scan(
                step, (caches, kv_lens, last_logits, key, done0), None,
                length=n_new)
            return caches, kv_lens, logits, toks.T

        return jax.jit(run)


def _default_out_proj(o2, layer):
    """Attention output projection on replicated weights — the default
    ``out_proj`` hook of the shared forwards below.  ``o2`` is the
    flattened attention output ``[rows, Hq*hd]``.  Tensor-parallel
    instantiations (serve/mesh.py) swap in a row-parallel matmul +
    ``psum`` over the local head shard."""
    return o2 @ layer["wo"]


def _token_forward(params, caches, token, pos, *, cfg: LlamaConfig,
                   write_kv, attend, ffn=None, out_proj=None):
    """ONE copy of the single-token decode layer math, parameterized by
    the cache addressing (ROADMAP: the shared (write_kv, attend) pair):

    - ``write_kv(li, cache, k, v) -> cache'`` appends the token's K/V
      ([B, Hkv, hd] each) into layer ``li``'s cache;
    - ``attend(li, q, cache) -> [B, Hq, hd]`` scores the query against
      the updated cache.

    ``Generator._step_impl`` (contiguous append + SP flash decode) and
    ``serve.engine._paged_decode_forward`` (pool-page scatter + the
    block-table kernel) are both this function with different pairs —
    the serve-engine oracle tests lock their bit-exactness.  ``pos``
    [B] int32 carries the RoPE positions (each row's cache length).

    ``out_proj(o2, layer) -> [B, D]`` swaps the attention output
    projection (with ``ffn``, the two seams a tensor-parallel
    instantiation must reduce across ranks — serve/mesh.py passes
    row-parallel matmul + psum hooks and a head-local ``cfg``)."""
    if ffn is None:
        ffn = _dense_prompt_ffn
    if out_proj is None:
        out_proj = _default_out_proj
    new_caches = []
    x = params["embed"][token]  # [B, D]
    for li, layer in enumerate(params["layers"]):
        h = _rms_norm(x[:, None], layer["attn_norm"], cfg.norm_eps)[:, 0]
        q = (h @ layer["wq"]).reshape(-1, cfg.n_heads, cfg.head_dim)
        k = (h @ layer["wk"]).reshape(-1, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ layer["wv"]).reshape(-1, cfg.n_kv_heads, cfg.head_dim)
        q = _rope_at(q, pos, cfg.rope_theta)
        k = _rope_at(k, pos, cfg.rope_theta)
        cache = write_kv(li, caches[li], k, v)
        o = attend(li, q, cache)  # [B, Hq, hd]
        x = x + out_proj(o.reshape(o.shape[0], -1).astype(cfg.dtype),
                         layer)
        h = _rms_norm(x[:, None], layer["mlp_norm"], cfg.norm_eps)[:, 0]
        x = x + ffn(h, layer)
        new_caches.append(cache)
    x = _rms_norm(x[:, None], params["final_norm"], cfg.norm_eps)[:, 0]
    logits = jnp.dot(x, params["lm_head"],
                     preferred_element_type=jnp.float32)
    return new_caches, logits


def _multitoken_forward(params, caches, chunk, pos, *, cfg: LlamaConfig,
                        write_kv, attend, ffn=None, out_proj=None):
    """ONE copy of the multi-token (speculative-verify) layer math,
    parameterized like :func:`_token_forward`:

    - ``write_kv(li, cache, k, v) -> cache'`` writes [B, T, Hkv, hd]
      rows at each row's own offset;
    - ``attend(li, q, cache) -> [B, T, Hq, hd]`` scores T queries per
      row through the multi-token decode kernel (the q_lens contract).

    ``_verify_forward`` (contiguous per-row writes) and
    ``serve.engine._paged_verify_forward`` (block-table addressing)
    share it.  ``pos`` [B, T] int32: global position of query t of row
    b (``kv_lens[b] + t``).  ``out_proj`` as in :func:`_token_forward`
    (the tensor-parallel reduction seam)."""
    if ffn is None:
        ffn = _dense_prompt_ffn
    if out_proj is None:
        out_proj = _default_out_proj
    B, T = chunk.shape
    hd = cfg.head_dim
    x = params["embed"][chunk]                        # [B, T, D]
    new_caches = []
    for li, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        h2 = h.reshape(B * T, cfg.dim)
        q = (h2 @ layer["wq"]).reshape(B, T, cfg.n_heads, hd)
        k = (h2 @ layer["wk"]).reshape(B, T, cfg.n_kv_heads, hd)
        v = (h2 @ layer["wv"]).reshape(B, T, cfg.n_kv_heads, hd)
        q = _rope_rows(q, pos, cfg.rope_theta)
        k = _rope_rows(k, pos, cfg.rope_theta)
        cache = write_kv(li, caches[li], k, v)
        o = attend(li, q, cache)                      # [B, T, Hq, hd]
        o = o.reshape(B * T, cfg.n_heads * hd).astype(cfg.dtype)
        x = x + out_proj(o, layer).reshape(B, T, cfg.dim)
        h2 = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps).reshape(
            B * T, cfg.dim)
        x = x + ffn(h2, layer).reshape(B, T, cfg.dim)
        new_caches.append(cache)
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return new_caches, jnp.dot(x, params["lm_head"],
                               preferred_element_type=jnp.float32)


def _attend_prefix(q, k_all, v_all, prefix_len, *, k_scale=None,
                   v_scale=None, impl="auto", interpret=False,
                   mesh=None, axis=None, window=0, soft_cap=0.0):
    """Chunk attention against the cache prefix + itself.

    q [B, c, Hq, hd]; k/v_all [B, Hkv, S, hd] (the full cache, chunk rows
    already written at [prefix, prefix+c)); position j is visible to chunk
    row i iff j <= prefix + i.  Scores are [c, S] — the bounded-memory
    core of chunked prefill.  Optional scales dequantize an int8 cache.

    Both cache dtypes ride the flash prefill kernel (``prefix_len`` is
    traced — it enters as scalar prefetch, one trace per extent); an
    int8 cache's scales fuse into the block loop (``_flash_kernel_i8``).
    With ``mesh``/``axis`` given and world > 1, the cache stays
    sequence-SHARDED: each device runs flash over its KV shard and the
    partials LSE-merge (``sp_flash_attention_shard`` — the decode SP
    recipe on prefill; r4).  The dense program below remains for
    ``impl="xla"`` and the non-divisible-extent world>1 corner.

    Dispatch note: attention here always runs ``impl="auto"`` — the
    model-level ``impl`` contract is about the COLLECTIVE kernels
    (models/llama.py:_attention records the same design), so
    ``impl="pallas"`` does not force flash onto shapes it cannot tile
    (head_dim < 128, non-divisible extents); only explicit ``"xla"``
    pins the dense program.  Flash's own strict-dispatch mode is
    exercised by tests/test_flash_attention.py and the kernel-reach spy
    in tests/test_chunked_prefill.py.
    """
    if impl != "xla":
        from triton_dist_tpu.kernels.flash_attention import (
            flash_attention,
            sp_flash_attention_shard,
        )
        from triton_dist_tpu.kernels.flash_decode import (
            gqa_decode_shard,
            sp_gqa_decode_shard,
        )

        qt = q.transpose(0, 2, 1, 3)                  # [B, Hq, c, hd]
        world = 1 if mesh is None else mesh.shape[axis]
        B, c = q.shape[0], q.shape[1]
        S_all = k_all.shape[2]
        # Small chunks (speculative verify: k draft tokens) ride the
        # MULTI-TOKEN DECODE kernel (r5): the queries are c*G block rows
        # instead of a 128-row-padded prefill q block, and the cache
        # streams once at the decode kernel's HBM-floor blocks.  The
        # prefill kernel keeps the large-chunk path (its q tiling wins
        # when c itself is MXU-sized).
        use_decode = c <= 32
        if world == 1:
            if use_decode:
                lens = jnp.full((B,), c, jnp.int32) + prefix_len
                out, _ = gqa_decode_shard(
                    q, k_all, v_all, lens, impl="auto",
                    interpret=interpret, k_scale=k_scale, v_scale=v_scale,
                    soft_cap=soft_cap, window=window)
                return out.astype(jnp.float32)
            out = flash_attention(
                qt, k_all, v_all, causal=True, q_offset=prefix_len,
                impl="auto", interpret=interpret, k_scale=k_scale,
                v_scale=v_scale, window=window, soft_cap=soft_cap)
            return out.transpose(0, 2, 1, 3).astype(jnp.float32)
        if use_decode and S_all % world == 0:
            from jax.sharding import PartitionSpec as P

            def spd(q_, k_, v_, lens_, *scs):
                ksc, vsc = scs if scs else (None, None)
                return sp_gqa_decode_shard(
                    q_, k_, v_, lens_, axis=axis, impl="auto",
                    interpret=interpret, k_scale=ksc, v_scale=vsc,
                    soft_cap=soft_cap, window=window)

            seq_spec = P(None, None, axis)
            lens = jnp.full((B,), c, jnp.int32) + prefix_len
            args = [q, k_all, v_all, lens]
            specs = [P(), seq_spec, seq_spec, P()]
            if k_scale is not None:
                args += [k_scale, v_scale]
                specs += [seq_spec, seq_spec]
            out = jax.shard_map(
                spd, mesh=mesh, in_specs=tuple(specs), out_specs=P(),
                check_vma=False,
            )(*args)
            return out.astype(jnp.float32)
        if k_all.shape[2] % world == 0:
            from jax.sharding import PartitionSpec as P

            def sp(qt_, k_, v_, off, *scs):
                ksc, vsc = scs if scs else (None, None)
                # The prefill kernel's window mask is GLOBAL-position
                # based (qpos = q_offset + i, kpos = me*s_loc + j), so
                # windowed SP chunked prefill just works; decode's window
                # is global too since r5 (unclipped window_lens per shard).
                return sp_flash_attention_shard(
                    qt_, k_, v_, axis=axis, causal=True, q_offset=off,
                    impl="auto", interpret=interpret, k_scale=ksc,
                    v_scale=vsc, soft_cap=soft_cap, window=window)

            seq_spec = P(None, None, axis)
            args = [qt, k_all, v_all, prefix_len]
            specs = [P(), seq_spec, seq_spec, P()]
            if k_scale is not None:
                args += [k_scale, v_scale]
                specs += [seq_spec, seq_spec]
            out = jax.shard_map(
                sp, mesh=mesh, in_specs=tuple(specs),
                out_specs=P(), check_vma=False,
            )(*args)
            return out.transpose(0, 2, 1, 3).astype(jnp.float32)
        # world > 1 with a non-divisible extent: the dense program below
        # is the only path that can live in the partitioned jit (a plain
        # pallas call cannot).
    B, c, Hq, hd = q.shape
    _, Hkv, S, _ = k_all.shape
    g = Hq // Hkv
    qf = q.astype(jnp.float32).reshape(B, c, Hkv, g, hd)
    logits = jnp.einsum("bchgd,bhsd->bhgcs", qf,
                        k_all.astype(jnp.float32)) / np.sqrt(hd)
    if k_scale is not None:
        logits = logits * k_scale[:, :, None, None, :]
    if soft_cap:
        logits = soft_cap * jnp.tanh(logits / soft_cap)
    pos = jnp.arange(S)[None, :]                     # [1, S]
    limit = prefix_len + jnp.arange(c)[:, None]      # [c, 1]
    mask = pos <= limit                              # [c, S]
    if window:
        mask = mask & (limit - pos < window)
    logits = jnp.where(mask[None, None, None], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    if v_scale is not None:
        p = p * v_scale[:, :, None, None, :]
    out = jnp.einsum("bhgcs,bhsd->bchgd", p, v_all.astype(jnp.float32))
    return out.reshape(B, c, Hq, hd)


def _write_chunk(cache, new, prefix_len, quantized):
    """Write chunk K or V rows [B, Hkv, c, hd] at ``prefix_len``; for a
    quantized cache dict, rows quantize and the scale plane updates too."""
    from triton_dist_tpu.kernels.flash_decode import quantize_kv

    if not quantized:
        return jax.lax.dynamic_update_slice(
            cache, new.astype(cache.dtype), (0, 0, prefix_len, 0))
    q8, s = quantize_kv(new)
    return {
        "q": jax.lax.dynamic_update_slice(cache["q"], q8,
                                          (0, 0, prefix_len, 0)),
        "s": jax.lax.dynamic_update_slice(cache["s"], s,
                                          (0, 0, prefix_len)),
    }


def _chunk_forward(params, chunk, caches, prefix_len, *, cfg: LlamaConfig,
                   quantized: bool, ffn=None, out_proj=None,
                   extent: int | None = None,
                   n_valid=None, impl: str = "auto", interpret: bool = False,
                   mesh=None, axis=None, attend=None):
    """One prompt chunk [B, c] against the cached prefix; returns
    (new_caches, logits [B, c, V] — position i predicts the token after
    chunk[:, i]).  The chunk's own K/V are written to the cache first
    (quantized if the cache is), then attention reads the cache back — so
    later chunks and the current one see identical (possibly quantized)
    K/V, matching the decode path's behavior.  Speculative verification
    (models/speculative.py) consumes the full per-position logits.
    ``extent`` (static) bounds the cache rows attention reads — scores
    stay [c, extent] instead of [c, max_seq].

    ``n_valid`` (traced scalar, optional) marks chunk rows >= n_valid as
    PADDING: their K/V write to the cache as exact zeros, so a final
    prompt chunk padded up to a fixed shape leaves the cache bit-identical
    to an unpadded run (pad rows match the zero-init rows it never wrote).
    Padded QUERY rows need no mask — causality already hides rows >=
    n_valid from every valid query (row i attends to positions <=
    prefix + i < prefix + n_valid), and their own logits are garbage the
    caller discards.  One trace serves every residual chunk length — the
    serving engine's admission path never retraces on prompt shape
    (docs/serving.md: the bucket ladder).

    ``out_proj`` as in :func:`_token_forward`: the attention output
    projection seam a tensor-parallel caller reduces across ranks
    (serve/mesh.py's head-sharded chunk prefill — there ``mesh``/
    ``axis`` stay None because the TP caller is already inside its own
    ``shard_map`` and the per-rank cache is head-local, not
    sequence-sharded).

    ``attend`` overrides the whole prefix-attention read:
    ``attend(q, k_view, v_view, prefix_len, k_scale=, v_scale=)`` on
    the extent-bounded cache views (scale views None unless
    ``quantized``).  serve/mesh.py's sequence-sharded chunk prefill
    supplies one that slices the rank-local span out of the views and
    LSE-combines across ranks — the K/V write above it stays whole, so
    cache contents never depend on the layout."""
    if ffn is None:
        ffn = _dense_prompt_ffn
    if out_proj is None:
        out_proj = _default_out_proj
    if attend is None:
        attend = functools.partial(_attend_prefix, impl=impl,
                                   interpret=interpret, mesh=mesh,
                                   axis=axis, window=cfg.attn_window,
                                   soft_cap=cfg.attn_soft_cap)
    B, c = chunk.shape
    hd = cfg.head_dim
    x = params["embed"][chunk]                       # [B, c, D]
    positions = prefix_len + jnp.arange(c, dtype=jnp.int32)
    pad_mask = (None if n_valid is None else
                (jnp.arange(c, dtype=jnp.int32) < n_valid)[None, :, None,
                                                           None])
    new_caches = []
    for li, layer in enumerate(params["layers"]):
        k_c, v_c = caches[li]
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        h2 = h.reshape(B * c, cfg.dim)
        q = (h2 @ layer["wq"]).reshape(B, c, cfg.n_heads, hd)
        k = (h2 @ layer["wk"]).reshape(B, c, cfg.n_kv_heads, hd)
        v = (h2 @ layer["wv"]).reshape(B, c, cfg.n_kv_heads, hd)
        q = _rope(q.transpose(1, 0, 2, 3), positions,
                  cfg.rope_theta).transpose(1, 0, 2, 3)
        k = _rope(k.transpose(1, 0, 2, 3), positions,
                  cfg.rope_theta).transpose(1, 0, 2, 3)
        if pad_mask is not None:
            k = jnp.where(pad_mask, k, jnp.zeros((), k.dtype))
            v = jnp.where(pad_mask, v, jnp.zeros((), v.dtype))
        k_c = _write_chunk(k_c, k.transpose(0, 2, 1, 3), prefix_len,
                           quantized)
        v_c = _write_chunk(v_c, v.transpose(0, 2, 1, 3), prefix_len,
                           quantized)
        new_caches.append((k_c, v_c))
        ext = extent or (k_c["q"] if quantized else k_c).shape[2]
        if quantized:
            o = attend(q, k_c["q"][:, :, :ext], v_c["q"][:, :, :ext],
                       prefix_len, k_scale=k_c["s"][:, :, :ext],
                       v_scale=v_c["s"][:, :, :ext])
        else:
            o = attend(q, k_c[:, :, :ext], v_c[:, :, :ext], prefix_len,
                       k_scale=None, v_scale=None)
        o = o.reshape(B * c, cfg.n_heads * hd).astype(cfg.dtype)
        x = x + out_proj(o, layer).reshape(B, c, cfg.dim)
        h2 = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps).reshape(
            B * c, cfg.dim)
        x = x + ffn(h2, layer).reshape(B, c, cfg.dim)
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return new_caches, jnp.dot(x, params["lm_head"],
                               preferred_element_type=jnp.float32)


def _rope_rows(x, pos, theta):
    """RoPE with PER-ROW positions: x [B, T, H, hd]; pos [B, T] int32."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (np.arange(0, hd, 2, dtype=np.float32) / hd))
    ang = pos[..., None].astype(jnp.float32) * freqs      # [B, T, hd/2]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def _write_rows(cache, new, offs):
    """Per-row chunk write: cache [B, Hkv, S, D] <- new [B, Hkv, T, D] at
    row offsets offs [B] (each request's own cache length).

    Rows whose write would overflow the cache (offs[b] + T > S) are
    SKIPPED, not clamped: dynamic_update_slice would clamp the offset and
    silently overwrite still-valid rows.  Retired rows in the batched
    speculative loop (and the serving engine) sit exactly there — their
    outputs are discarded, but their caches must stay intact (ADVICE r5
    finding #2)."""
    T = new.shape[2]
    ok = offs + T <= cache.shape[2]                   # [B] bool

    def per(c, n, o, keep):
        upd = jax.lax.dynamic_update_slice(c, n.astype(c.dtype), (0, o, 0))
        return jnp.where(keep, upd, c)

    return jax.vmap(per)(cache, new, offs, ok)


def _verify_forward(params, chunk, caches, kv_lens, *, cfg: LlamaConfig,
                    impl: str = "auto", interpret: bool = False,
                    ffn=None):
    """Batched speculative-verify forward (r5): score chunk [B, T] draft
    tokens against PER-ROW cache lengths ``kv_lens`` [B] in one pass.

    The per-row machinery `_chunk_forward` cannot express (its
    ``prefix_len`` is one scalar): RoPE at positions kv_lens[b] + t,
    K/V written at per-row offsets, and attention through the
    MULTI-TOKEN decode kernel (q_lens path — query t of row b sits at
    global position kv_lens[b] + t, exactly the kernel's
    ``pos < wlen - (T-1-t)`` rule).  Returns (new_caches,
    logits [B, T, V]).  World-1, float caches (the batch-1 path keeps
    full SP + int8 support via `_chunk_forward`).
    """
    from triton_dist_tpu.kernels.flash_decode import gqa_decode_shard

    T = chunk.shape[1]
    pos = kv_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None]

    def write_kv(li, cache, k, v):
        k_c, v_c = cache
        return (_write_rows(k_c, k.transpose(0, 2, 1, 3), kv_lens),
                _write_rows(v_c, v.transpose(0, 2, 1, 3), kv_lens))

    def attend(li, q, cache):
        o, _ = gqa_decode_shard(q, cache[0], cache[1], kv_lens + T,
                                impl=impl, interpret=interpret,
                                soft_cap=cfg.attn_soft_cap,
                                window=cfg.attn_window)
        return o

    return _multitoken_forward(params, caches, chunk, pos, cfg=cfg,
                               write_kv=write_kv, attend=attend, ffn=ffn)


def _dense_prompt_ffn(h2, layer):
    """The dense family's SwiGLU MLP over flattened prompt tokens."""
    act = (jax.nn.silu((h2 @ layer["wgate"]).astype(jnp.float32))
           .astype(h2.dtype) * (h2 @ layer["wup"]))
    return act @ layer["wdown"]


def _prompt_forward(params, tokens, *, cfg: LlamaConfig, ffn=None,
                    impl: str = "auto", interpret: bool = False):
    """Full-sequence forward on replicated weights that also returns the
    per-layer K/V (post-RoPE, cache layout [B, Hkv, S, hd]) and logits.

    ``ffn(h2, layer) -> [B*S, D]`` swaps the MLP — the MoE family
    (generate_moe.py) reuses the whole attention/cache body this way.
    """
    from triton_dist_tpu.kernels.flash_attention import flash_gqa_attention

    if ffn is None:
        ffn = _dense_prompt_ffn
    B, S = tokens.shape
    hd = cfg.head_dim
    x = params["embed"][tokens]          # [B, S, D]
    positions = jnp.arange(S, dtype=jnp.int32)
    kvs = []
    for layer in params["layers"]:
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        h2 = h.reshape(B * S, cfg.dim)
        q = (h2 @ layer["wq"]).reshape(B, S, cfg.n_heads, hd)
        k = (h2 @ layer["wk"]).reshape(B, S, cfg.n_kv_heads, hd)
        v = (h2 @ layer["wv"]).reshape(B, S, cfg.n_kv_heads, hd)
        # _rope expects [S, B, H, hd] (seq-major).
        q = _rope(q.transpose(1, 0, 2, 3), positions, cfg.rope_theta)
        k = _rope(k.transpose(1, 0, 2, 3), positions, cfg.rope_theta)
        v = v.transpose(1, 0, 2, 3)
        kvs.append((k.transpose(1, 2, 0, 3), v.transpose(1, 2, 0, 3)))
        o = flash_gqa_attention(q, k, v, causal=True,
                                scale=1.0 / np.sqrt(hd),
                                impl="xla" if impl == "xla" else "auto",
                                interpret=interpret,
                                window=cfg.attn_window,
                                soft_cap=cfg.attn_soft_cap)
        o = o.transpose(1, 0, 2, 3).reshape(B * S, cfg.n_heads * hd)
        x = x + (o @ layer["wo"]).reshape(B, S, cfg.dim)
        h2 = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps).reshape(
            B * S, cfg.dim)
        x = x + ffn(h2, layer).reshape(B, S, cfg.dim)
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.dot(x, params["lm_head"],
                     preferred_element_type=jnp.float32)
    return kvs, logits
