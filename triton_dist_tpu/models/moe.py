"""MoE transformer (Mixtral/DeepSeek-class), expert-parallel and kernel-wired.

The second model family: the Llama attention/TP stack (models/llama.py) with
the dense FFN replaced by a top-k routed expert FFN running over the
framework's EP machinery — token dispatch/combine through the low-latency
AllToAll (kernels/all_to_all.py, differentiable via its custom VJP) and
expert compute through the grouped Pallas GEMM (kernels/group_gemm.py) fed
by the device-side sort/align (kernels/moe_utils.py).

Reference analog: the reference exercises its MoE path as kernel tests
(test_ep_moe_inference.py, test_ag_moe.py, test_moe_reduce_rs.py with
Qwen/DeepSeek FFN shapes) and an inference layer (``EPAll2AllLayer``); it
has no MoE *model* and no training story.  Here the same machinery runs as
a full transformer with a train step — gradients flow through the AllToAll
(its transpose is the inverse AllToAll), the scatter/gather routing, and
the grouped GEMMs.

Parallelism layout (one mesh axis, Megatron-style + EP):

* Attention: TP over heads, sequence-parallel residual stream — identical
  to the Llama model (shared code).
* MoE FFN: experts sharded over the same axis (expert ``e`` lives on rank
  ``e // (E // world)``, the reference's contiguous layout); tokens travel
  to their experts and back each block.
* Router: replicated; aux load-balance loss (Switch-style) accumulated
  across layers.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.group_gemm import moe_ffn_sorted
from triton_dist_tpu.kernels.moe_utils import (
    gather_sorted,
    sort_align,
    topk_routing,
)
from triton_dist_tpu.layers.ep_a2a import ep_combine_shard, ep_dispatch_shard
from triton_dist_tpu.models.llama import (
    LlamaConfig,
    _rms_norm,
    attention_block_shard,
)


@dataclass(frozen=True)
class MoEConfig:
    vocab: int = 512
    dim: int = 256
    n_layers: int = 2
    n_heads: int = 8
    n_kv_heads: int = 4
    n_experts: int = 8
    topk: int = 2
    expert_ffn_dim: int = 256
    max_seq: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    aux_loss_coef: float = 0.01
    # group-GEMM row-tile size; also the expert padding granularity.
    block_m: int = 128
    # per-destination-rank token capacity; None = lossless worst case
    # (t_loc * topk, every local assignment bound for one rank).
    max_tokens: int | None = None
    dtype: object = jnp.float32
    # Attention variants (r4), same semantics as LlamaConfig.
    attn_window: int = 0
    attn_soft_cap: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def as_llama(self) -> LlamaConfig:
        """Attention-side view (shared _rope/_attention take a LlamaConfig)."""
        return LlamaConfig(
            vocab=self.vocab, dim=self.dim, n_layers=self.n_layers,
            n_heads=self.n_heads, n_kv_heads=self.n_kv_heads,
            ffn_dim=self.expert_ffn_dim, max_seq=self.max_seq,
            rope_theta=self.rope_theta, norm_eps=self.norm_eps,
            dtype=self.dtype, attn_window=self.attn_window,
            attn_soft_cap=self.attn_soft_cap)

    @staticmethod
    def mixtral_8x7b() -> "MoEConfig":
        """Mixtral-8x7B shapes (the DeepEP/EP-MoE benchmark class)."""
        return MoEConfig(vocab=32000, dim=4096, n_layers=32, n_heads=32,
                         n_kv_heads=8, n_experts=8, topk=2,
                         expert_ffn_dim=14336, dtype=jnp.bfloat16)

    @staticmethod
    def deepseek_moe() -> "MoEConfig":
        """The reference's low-latency AllToAll benchmark config
        (README.md:87 / test_all_to_all.py: 128 experts, topk 8,
        hidden 7168 — the DeepSeek-V3 serving point)."""
        return MoEConfig(vocab=129280, dim=7168, n_layers=61, n_heads=128,
                         n_kv_heads=128, n_experts=128, topk=8,
                         expert_ffn_dim=2048, dtype=jnp.bfloat16)

    @staticmethod
    def tiny(dtype=jnp.float32) -> "MoEConfig":
        """CPU-mesh test size (block_m small enough for tiny token counts)."""
        # Per-shard pallas-legal on a tp=4 mesh (strict impl='pallas'
        # gate): head_dim 128 keeps kv/o projections at n%128/k%128 per
        # device; expert_ffn 512 leaves f_loc = 128.
        return MoEConfig(vocab=512, dim=512, n_layers=2, n_heads=4,
                         n_kv_heads=4, n_experts=8, topk=2,
                         expert_ffn_dim=512, max_seq=128, block_m=8,
                         dtype=dtype)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def init_params(cfg: MoEConfig, key) -> dict:
    """Expert stacks are full [E, ...] arrays; ``param_specs`` shards their
    leading (expert) dim over the mesh axis — EP by construction."""
    hd = cfg.head_dim

    def dense(k, fan_in, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(cfg.dtype)

    keys = jax.random.split(key, 2 + cfg.n_layers)
    params = {
        "embed": dense(keys[0], 1, (cfg.vocab, cfg.dim)),
        "lm_head": dense(keys[1], cfg.dim, (cfg.dim, cfg.vocab)),
        "final_norm": jnp.ones((cfg.dim,), cfg.dtype),
        "layers": [],
    }
    E, F = cfg.n_experts, cfg.expert_ffn_dim
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[2 + i], 9)
        params["layers"].append({
            "attn_norm": jnp.ones((cfg.dim,), cfg.dtype),
            "mlp_norm": jnp.ones((cfg.dim,), cfg.dtype),
            "wq": dense(lk[0], cfg.dim, (cfg.dim, cfg.n_heads * hd)),
            "wk": dense(lk[1], cfg.dim, (cfg.dim, cfg.n_kv_heads * hd)),
            "wv": dense(lk[2], cfg.dim, (cfg.dim, cfg.n_kv_heads * hd)),
            "wo": dense(lk[3], cfg.n_heads * hd, (cfg.n_heads * hd, cfg.dim)),
            # Router in fp32: routing decisions are precision-sensitive.
            "router": (jax.random.normal(lk[4], (cfg.dim, E), jnp.float32)
                       / math.sqrt(cfg.dim)),
            "w_gate": dense(lk[5], cfg.dim, (E, cfg.dim, F)),
            "w_up": dense(lk[6], cfg.dim, (E, cfg.dim, F)),
            "w_down": dense(lk[7], F, (E, F, cfg.dim)),
        })
    return params


def param_specs(cfg: MoEConfig, axis: str = "tp") -> dict:
    layer = {
        "attn_norm": P(), "mlp_norm": P(),
        "wq": P(None, axis), "wk": P(None, axis), "wv": P(None, axis),
        "wo": P(axis, None),
        "router": P(),
        "w_gate": P(axis, None, None),   # EP: expert dim sharded
        "w_up": P(axis, None, None),
        "w_down": P(axis, None, None),
    }
    return {
        "embed": P(), "lm_head": P(), "final_norm": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


# ---------------------------------------------------------------------------
# MoE FFN (shard level)
# ---------------------------------------------------------------------------


def moe_ffn_shard(h2, layer, cfg: MoEConfig, *, axis, impl, interpret):
    """Routed expert FFN over local tokens h2 [T_loc, D].

    dispatch (AllToAll) → sort received tokens by local expert →
    grouped-GEMM SwiGLU → inverse AllToAll → topk-weighted combine.
    Returns (out [T_loc, D], aux_loss_contribution scalar).
    """
    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    E = cfg.n_experts
    epr = E // world
    t_loc = h2.shape[0]

    logits = jnp.dot(h2.astype(jnp.float32), layer["router"])
    weights, experts = topk_routing(logits, cfg.topk)

    # Switch-style load-balance aux: E * sum_e f_e * p_e over LOCAL tokens
    # (f = fraction of assignments to e, p = mean router prob of e).  The
    # global aux is the mean over devices of these local-batch values (the
    # standard per-group variant — balancing each device's own dispatch is
    # what bounds EP capacity overflow), not the single-global-batch value.
    probs = jax.nn.softmax(logits, axis=-1)
    frac = (jnp.zeros((E,), jnp.float32)
            .at[experts.reshape(-1)].add(1.0) / (t_loc * cfg.topk))
    aux = E * jnp.sum(frac * jnp.mean(probs, axis=0)) / world

    # zero_undefined: this is the TRAINING path — recv feeds differentiated
    # matmuls, whose weight gradients contract over padding rows too
    # (0-cotangent x NaN-garbage = NaN without the mask).
    recv, recv_expert, _splits, plan, _dropped = ep_dispatch_shard(
        h2.astype(cfg.dtype), experts, axis=axis, n_experts=E,
        max_tokens=cfg.max_tokens, impl=impl, interpret=interpret,
        zero_undefined=True)
    max_tokens = recv.shape[1]  # dispatch owns the None→worst-case rule

    # Local expert compute over the received buffer.  Zero (padding) rows
    # pass through the bias-free FFN as zeros, so steering them to expert 0
    # is harmless; their contributions are masked again at combine.
    T = world * max_tokens
    local_e = jnp.clip(recv_expert.reshape(T, 1) - me * epr, 0, epr - 1)
    splan = sort_align(local_e, epr, cfg.block_m)
    x_sorted = gather_sorted(recv.reshape(T, cfg.dim), splan["dest"],
                             splan["m_pad"])
    y_sorted = moe_ffn_sorted(
        x_sorted, layer["w_gate"], layer["w_up"], layer["w_down"],
        splan["tile_expert"], block_m=cfg.block_m, impl=impl,
        interpret=interpret)
    y = y_sorted[splan["dest"]].reshape(world, max_tokens, cfg.dim)

    out = ep_combine_shard(y, weights, plan, axis=axis, impl=impl,
                           interpret=interpret)
    return out.astype(cfg.dtype), aux


def moe_block_shard(x, layer, cfg: MoEConfig, *, axis, impl, interpret):
    """MoE FFN sub-block with residual: RMSNorm → routed expert FFN.
    x: [S_loc, B, D].  Returns (x', aux contribution).  Shared by the plain
    forward and the pipelined path (models/pp.py)."""
    s_loc, b, _ = x.shape
    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    out, aux = moe_ffn_shard(h.reshape(s_loc * b, cfg.dim), layer, cfg,
                             axis=axis, impl=impl, interpret=interpret)
    return x + out.reshape(s_loc, b, cfg.dim), aux


# ---------------------------------------------------------------------------
# Forward / loss (shard level)
# ---------------------------------------------------------------------------


def forward_shard(params, tokens_shard, cfg: MoEConfig, *, axis="tp",
                  impl="auto", interpret=False):
    """Per-device forward.  tokens_shard [S_loc, B] int32, sequence sharded.
    Returns (logits [S_loc, B, vocab] fp32, aux_loss scalar)."""
    lcfg = cfg.as_llama()
    world = jax.lax.axis_size(axis)
    assert cfg.n_heads % world == 0 and cfg.n_kv_heads % world == 0
    assert cfg.n_experts % world == 0

    s_loc, b = tokens_shard.shape
    x = params["embed"][tokens_shard]  # [S_loc, B, D]
    aux_total = jnp.float32(0.0)

    for layer in params["layers"]:
        # --- attention (TP over heads; shared Llama code path) ---
        x = attention_block_shard(x, layer, lcfg, axis=axis, impl=impl,
                                  interpret=interpret)
        # --- MoE FFN (EP over the same axis) ---
        x, aux = moe_block_shard(x, layer, cfg, axis=axis, impl=impl,
                                 interpret=interpret)
        aux_total = aux_total + aux

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.dot(x, params["lm_head"], preferred_element_type=jnp.float32)
    return logits, aux_total


def loss_shard(params, tokens_shard, targets_shard, cfg: MoEConfig, *,
               axis="tp", dp_axis=None, impl="auto", interpret=False):
    """Per-device contribution to global mean CE + aux balance loss (psum of
    this over all devices == the global loss; see llama.loss_shard for why
    the psum must stay outside autodiff)."""
    logits, aux = forward_shard(params, tokens_shard, cfg, axis=axis,
                                impl=impl, interpret=interpret)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets_shard[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    denom = ll.size * jax.lax.axis_size(axis)
    if dp_axis is not None:
        denom = denom * jax.lax.axis_size(dp_axis)
        # aux from forward_shard is already divided by the EP axis size
        # (per-device contribution); spread it over the dp copies too.
        aux = aux / jax.lax.axis_size(dp_axis)
    return -jnp.sum(ll) / denom + cfg.aux_loss_coef * aux


# ---------------------------------------------------------------------------
# Host-level entries (mirror models/llama.py)
# ---------------------------------------------------------------------------


def make_forward(cfg: MoEConfig, mesh: Mesh, *, axis="tp", dp_axis=None,
                 impl="auto", interpret=False):
    batch_spec = P(axis, dp_axis) if dp_axis else P(axis)
    specs = param_specs(cfg, axis)
    all_axes = (axis,) if dp_axis is None else (axis, dp_axis)

    def fwd_shard(params, tokens):
        logits, aux = forward_shard(params, tokens, cfg, axis=axis,
                                    impl=impl, interpret=interpret)
        # aux is a per-device contribution; the psum (safe here — this
        # entry is not differentiated) reports the global value.
        n_dp = 1 if dp_axis is None else jax.lax.axis_size(dp_axis)
        return logits, jax.lax.psum(aux / n_dp, all_axes)

    fn = jax.shard_map(
        fwd_shard,
        mesh=mesh,
        in_specs=(specs, batch_spec),
        out_specs=(P(axis, dp_axis) if dp_axis else P(axis), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def make_train_step(cfg: MoEConfig, mesh: Mesh, *, axis="tp", dp_axis=None,
                    impl="auto", interpret=False, lr=1e-3):
    """SGD step through attention TP kernels, the AllToAll VJP, and the
    grouped GEMMs.  Same reduction logic as llama.make_train_step: leaves
    whose spec mentions ``axis`` hold complete local grads; replicated
    leaves psum over ``axis``; everything sums over ``dp_axis``."""
    specs = param_specs(cfg, axis)
    batch_spec = P(axis, dp_axis) if dp_axis else P(axis)

    def step_shard(params, tokens, targets):
        local_loss, grads = jax.value_and_grad(loss_shard)(
            params, tokens, targets, cfg, axis=axis, dp_axis=dp_axis,
            impl=impl, interpret=interpret)
        all_axes = (axis,) if dp_axis is None else (axis, dp_axis)
        loss = jax.lax.psum(local_loss, all_axes)

        def _reduce(g, spec):
            sharded_on_axis = any(s == axis for s in spec)
            axes = () if sharded_on_axis else (axis,)
            if dp_axis is not None:
                axes = axes + (dp_axis,)
            return jax.lax.psum(g, axes) if axes else g

        grads = jax.tree.map(_reduce, grads, specs,
                             is_leaf=lambda x: isinstance(x, P))
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        return new_params, loss

    fn = jax.shard_map(
        step_shard,
        mesh=mesh,
        in_specs=(specs, batch_spec, batch_spec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(fn), specs


def place_params(params, cfg: MoEConfig, mesh: Mesh, axis="tp") -> dict:
    specs = param_specs(cfg, axis)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)
