"""End-to-end model families wired through the distributed kernels.

Reference analog: the reference ships no trainer — its model story is the
LLaMA-shape test configs (test_ag_gemm.py ``--shape_id``) and inference
layers.  The TPU build provides actual models: a Llama-style dense
transformer (``llama.py``) and a Mixtral-style MoE (``moe.py``), both
running forward AND backward through the overlapped kernels.
"""

from triton_dist_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    init_params,
    forward_shard,
    loss_shard,
    make_forward,
    make_train_step,
)
from triton_dist_tpu.models.moe import (  # noqa: F401
    MoEConfig,
    init_params as moe_init_params,
    make_forward as moe_make_forward,
    make_train_step as moe_make_train_step,
    place_params as moe_place_params,
)
from triton_dist_tpu.models.pp import (  # noqa: F401
    init_pp_params,
    make_pp_train_step,
    place_pp_params,
    pp_param_specs,
)
from triton_dist_tpu.models.cp import (  # noqa: F401
    cp_param_specs,
    make_cp_forward,
    make_cp_train_step,
    place_cp_params,
)
from triton_dist_tpu.models.generate import (  # noqa: F401
    GenerationState,
    Generator,
)
from triton_dist_tpu.models.generate_moe import (  # noqa: F401
    MoEGenerator,
    place_params_serving,
)
from triton_dist_tpu.models.sampling import (  # noqa: F401
    make_sampler,
    sample_logits,
)
from triton_dist_tpu.models.llama_w8a8 import (  # noqa: F401
    make_w8a8_forward,
    place_w8a8_params,
    quantize_params_w8a8,
)
from triton_dist_tpu.models.beam import beam_search  # noqa: F401
from triton_dist_tpu.models.speculative import (  # noqa: F401
    SpeculativeGenerator,
    SpeculativeSampler,
)
