"""Token sampling for autoregressive serving: temperature, top-k, top-p.

The reference stops at the decode-attention kernel (no sampling — its
serving story ends at logits); a usable serving stack needs the sampler.
All transforms are shape-static and jit-compatible (``lax.top_k`` + sorted
cumulative mass for nucleus filtering — no data-dependent shapes), so one
compiled sampler serves every step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalar, NOT jnp: a module-level jnp constant would initialize the
# JAX backend at import time (breaks dryrun_multichip's late CPU pinning).
NEG_INF = np.float32(-1e30)


def _apply_top_k(logits, top_k: int):
    """Keep the k highest logits per row, mask the rest to -inf."""
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # [B, 1]
    return jnp.where(logits < kth, NEG_INF, logits)


def _apply_top_p(logits, top_p):
    """Nucleus filtering: keep the smallest prefix of the probability-sorted
    vocab whose total mass reaches ``top_p`` (the top token always stays).

    ``top_p`` may be a python float (the static scalar path) or a
    broadcastable ``[..., 1]`` array (the per-row traced path of
    :func:`sample_logits_rowwise`) — the masking rule is THE one copy of
    the nucleus math either way."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Row below which (exclusive prefix mass >= top_p) → cut.  Shifting by
    # one keeps the first token crossing the threshold.
    cut = cum - probs >= top_p
    # The top token is unconditionally kept (guards top_p <= p(top) —
    # including top_p=0.0, which would otherwise cut the whole vocab and
    # degenerate categorical() to always-token-0).
    idx = jax.lax.broadcasted_iota(jnp.int32, cut.shape, cut.ndim - 1)
    cut = cut & (idx > 0)
    # Cutoff = smallest KEPT logit (mask cut rows to +inf before the min).
    cutoff = jnp.where(cut, jnp.float32(jnp.inf), sorted_logits).min(
        axis=-1, keepdims=True)
    return jnp.where(logits < cutoff, NEG_INF, logits)


def _filtered_logits(logits, temperature: float, top_k, top_p):
    """The single temperature → top-k → top-p pipeline every sampling
    surface shares (direct sampling AND speculative verification — the
    rejection-sampling identity needs both sides to filter identically).

    ``temperature`` must be > 0: greedy is a separate code path
    (:func:`sample_logits` special-cases it to argmax before reaching here,
    and a greedy *distribution* is a one-hot, not a softmax limit we can
    divide our way to)."""
    if not temperature > 0.0:
        raise ValueError(
            f"temperature must be > 0, got {temperature}; use "
            "sample_logits(temperature=0) for greedy decoding")
    x = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0 and top_k < x.shape[-1]:
        x = _apply_top_k(x, top_k)
    if top_p is not None and top_p < 1.0:
        x = _apply_top_p(x, top_p)
    return x


@functools.partial(jax.jit,
                   static_argnames=("temperature", "top_k", "top_p"))
def filtered_probs(logits, *, temperature: float = 1.0,
                   top_k: int | None = None,
                   top_p: float | None = None) -> jax.Array:
    """logits [..., V] → the post-filter sampling distribution π [..., V]
    (exactly what :func:`sample_logits` draws from)."""
    return jax.nn.softmax(_filtered_logits(logits, temperature, top_k,
                                           top_p), axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("temperature", "top_k", "top_p"))
def sample_logits(logits, key, *, temperature: float = 1.0,
                  top_k: int | None = None,
                  top_p: float | None = None) -> jax.Array:
    """logits [B, vocab] f32 → token [B] int32.

    ``temperature=0`` is greedy argmax; filters compose as top-k then top-p
    (the standard serving order).
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    x = _filtered_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, x, axis=-1).astype(jnp.int32)


def sample_logits_rowwise(logits, keys, *, temperature, top_k, top_p,
                          greedy) -> jax.Array:
    """Fully-traceable PER-ROW sampler: every knob is a ``[B]`` array, so
    one compiled program serves a batch mixing greedy and sampled requests
    with different temperatures/filters — the sampler the serving engine's
    device-resident decode horizon runs *inside* its fused multi-step scan
    (`serve/engine.py`), where a host round trip per token is exactly what
    it exists to avoid.

    - ``logits`` [B, V] f32, ``keys`` [B] typed PRNG keys;
    - ``temperature`` [B] f32 (> 0 for sampled rows; greedy rows ignore it),
      ``top_k`` [B] int32 (0 disables), ``top_p`` [B] f32 (1.0 disables),
      ``greedy`` [B] bool (argmax, no randomness consumed).

    Row ``b``'s draw is BIT-IDENTICAL to the host fallback
    ``sample_logits(logits[b:b+1], keys[b], temperature=t_b, ...)`` —
    there is one copy of the filter math (temperature scale, the k-th
    largest value cut, :func:`_apply_top_p`), and the per-row draw is the
    same ``jax.random.categorical`` under ``vmap``
    (tests/test_sampling.py pins the equality, so the engine's H=1 host
    path and H>1 device path emit the same streams)."""
    gr = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    V = logits.shape[-1]
    # Greedy rows divide by a dummy 1.0 (their draw is discarded by the
    # final select) — temperature 0 must never reach the division.
    t = jnp.where(greedy, jnp.float32(1.0), temperature.astype(jnp.float32))
    x = logits.astype(jnp.float32) / t[:, None]
    # top-k: mask below the k-th largest VALUE per row (what lax.top_k
    # gives the static path); rows with the filter off keep x untouched,
    # exactly like the static path's skip.
    k = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
    srt = jnp.sort(x, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(srt, (k - 1)[:, None], axis=-1)
    x = jnp.where(((top_k > 0) & (top_k < V))[:, None],
                  jnp.where(x < kth, NEG_INF, x), x)
    x = jnp.where((top_p < 1.0)[:, None],
                  _apply_top_p(x, top_p[:, None].astype(jnp.float32)), x)
    drawn = jax.vmap(
        lambda kk, row: jax.random.categorical(kk, row[None], axis=-1)[0]
    )(keys, x).astype(jnp.int32)
    return jnp.where(greedy, gr, drawn)


def sample_positions_rowwise(logits, base_keys, counts, *, temperature,
                             top_k, top_p, greedy) -> jax.Array:
    """Multi-position view of :func:`sample_logits_rowwise`: ``logits``
    [B, T, V] → tokens [B, T], where position ``t`` of row ``b`` draws
    with the key ``fold_in(base_keys[b], counts[b] + t)`` — i.e. exactly
    the token the engine's per-row stream emits at emission index
    ``counts[b] + t``, no matter which surface emits it (the host
    ``_choose_token`` fallback, the fused decode horizon's scan, or a
    speculative round's accept chain scoring k+1 candidate positions at
    once).  One draw per (row, emission index) is the invariant that
    makes every decode path bit-interchangeable mid-request."""
    def at(t, lg):
        keys = jax.vmap(jax.random.fold_in)(base_keys, counts + t)
        return sample_logits_rowwise(lg, keys, temperature=temperature,
                                     top_k=top_k, top_p=top_p,
                                     greedy=greedy)

    T = logits.shape[1]
    return jax.vmap(at, in_axes=(0, 1), out_axes=1)(
        jnp.arange(T, dtype=counts.dtype), logits)


def make_sampler(*, temperature: float = 1.0, top_k: int | None = None,
                 top_p: float | None = None):
    """``sample(logits, key) -> token`` with the knobs baked in (one
    compiled executable reused across decode steps)."""
    return functools.partial(sample_logits, temperature=temperature,
                             top_k=top_k, top_p=top_p)
