"""Beam search over the sequence-parallel KV cache.

Serving-side addition beyond the reference.  Beams ride the generator's
batch dimension: prefill replicates the prompt per beam, every step scores
all beams in one batched decode, and the top ``num_beams`` (sequence,
continuation) pairs survive.  Beam reordering gathers the KV caches along
the batch axis — a [beams, H, S, D] take per layer, which XLA fuses with
the step's cache update.

Scoring is the standard sum of token log-probs with optional length
normalization (score / len**alpha at the end).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.generate import GenerationState, Generator


def _gather_cache(cache, idx):
    """Reorder one cache (float array or int8 dict) along the batch dim."""
    if isinstance(cache, dict):
        return {"q": cache["q"][idx], "s": cache["s"][idx]}
    return cache[idx]


def beam_search(gen: Generator, params, prompt, n_new: int, *,
                num_beams: int = 4, length_alpha: float = 0.0):
    """Beam-decode ``n_new`` tokens for ``prompt`` [1, S0].

    Returns (tokens [1, n_new] — the best beam's continuation,
    score float — its total log-prob, length-normalized when
    ``length_alpha`` > 0).
    """
    assert prompt.shape[0] == 1, "beam search takes a single prompt"
    B = num_beams
    state = gen.prefill(params, jnp.repeat(prompt, B, axis=0))

    logprobs = jax.nn.log_softmax(state.last_logits, axis=-1)  # [B, V]
    V = logprobs.shape[-1]
    # First step: all beams are identical — expand from beam 0 only.
    first = jax.lax.top_k(logprobs[0], B)
    scores = first[0]                                  # [B]
    seqs = np.asarray(first[1]).reshape(B, 1)          # [B, 1] host-side
    token = first[1].astype(jnp.int32)                 # [B]

    for _step in range(1, n_new + 1):
        state = gen.step(params, state, token)
        if _step == n_new:
            break
        logprobs = jax.nn.log_softmax(state.last_logits, axis=-1)
        total = scores[:, None] + logprobs               # [B, V]
        flat = total.reshape(-1)
        top = jax.lax.top_k(flat, B)
        scores = top[0]
        beam_idx = (top[1] // V).astype(jnp.int32)       # [B]
        token = (top[1] % V).astype(jnp.int32)
        # Reorder host-side sequences and device-side caches by beam.
        bi = np.asarray(beam_idx)
        seqs = np.concatenate([seqs[bi], np.asarray(token)[:, None]],
                              axis=1)
        state = GenerationState(
            caches=[(_gather_cache(k, beam_idx), _gather_cache(v, beam_idx))
                    for (k, v) in state.caches],
            kv_lens=state.kv_lens,
            last_logits=state.last_logits[beam_idx])

    if length_alpha > 0:
        scores = scores / (seqs.shape[1] ** length_alpha)
    best = int(jnp.argmax(scores))
    return jnp.asarray(seqs[best][None], jnp.int32), float(scores[best])
