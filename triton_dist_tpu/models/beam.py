"""Beam search over the sequence-parallel KV cache.

Serving-side addition beyond the reference.  Beams ride the generator's
batch dimension: prefill replicates the prompt per beam, every step scores
all beams in one batched decode, and the top ``num_beams`` (sequence,
continuation) pairs survive.  Beam reordering gathers the KV caches along
the batch axis — a [beams, H, S, D] take per layer, which XLA fuses with
the step's cache update.

:func:`beam_search` physically replicates the prompt KV ``num_beams``
times (and re-gathers whole caches on every reorder) — the contiguous
SP/int8-capable baseline.  :func:`beam_search_paged` replaces both
copies with **shared paged blocks**: every beam's block table maps the
prompt's pages read-only (refcount = beams), divergence copy-on-writes
exactly the one partially-filled tail page, and a reorder is a table
remap (surviving beams share their parent's pages; only the tail splits
again) — prompt KV memory is paid once regardless of beam width, the
prefix-cache sharing machinery of ``serve/block_manager.py`` applied to
N-best decoding (docs/serving.md "Prefix caching").

Scoring is the standard sum of token log-probs (no length normalization —
see ``beam_search``'s docstring for why the knob is deliberately absent).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.generate import GenerationState, Generator


def _map_cache(cache, fn):
    """Apply ``fn`` to one cache's arrays (float array or int8 dict)."""
    if isinstance(cache, dict):
        return {"q": fn(cache["q"]), "s": fn(cache["s"])}
    return fn(cache)


def beam_search(gen: Generator, params, prompt, n_new: int, *,
                num_beams: int = 4):
    """Beam-decode ``n_new`` tokens for ``prompt`` [1, S0].

    Returns (tokens [1, n_new] — the best beam's continuation, score
    float — its total log-prob).  All beams have the same length (no EOS
    handling), so GNMT-style length normalization would not change the
    winner and is deliberately not offered.
    """
    assert prompt.shape[0] == 1, "beam search takes a single prompt"
    B = num_beams
    # Prefill ONCE; replicate the resulting caches/logits per beam (the
    # beams only diverge from the first generated token on).
    s1 = gen.prefill(params, prompt)
    rep = lambda a: jnp.repeat(a, B, axis=0)  # noqa: E731
    state = GenerationState(
        caches=[(_map_cache(k, rep), _map_cache(v, rep))
                for (k, v) in s1.caches],
        kv_lens=rep(s1.kv_lens),
        last_logits=rep(s1.last_logits))

    logprobs = jax.nn.log_softmax(state.last_logits, axis=-1)  # [B, V]
    V = logprobs.shape[-1]
    # First expansion: all beams are identical — expand from beam 0 only.
    first = jax.lax.top_k(logprobs[0], B)
    scores = first[0]                                  # [B]
    seqs = np.asarray(first[1]).reshape(B, 1)          # [B, 1] host-side
    token = first[1].astype(jnp.int32)                 # [B]

    for _step in range(n_new - 1):
        state = gen.step(params, state, token)
        logprobs = jax.nn.log_softmax(state.last_logits, axis=-1)
        total = scores[:, None] + logprobs               # [B, V]
        top = jax.lax.top_k(total.reshape(-1), B)
        scores = top[0]
        beam_idx = (top[1] // V).astype(jnp.int32)       # [B]
        token = (top[1] % V).astype(jnp.int32)
        # Reorder host-side sequences and device-side caches by beam.
        bi = np.asarray(beam_idx)
        seqs = np.concatenate([seqs[bi], np.asarray(token)[:, None]],
                              axis=1)
        take = lambda a: a[beam_idx]  # noqa: E731
        state = GenerationState(
            caches=[(_map_cache(k, take), _map_cache(v, take))
                    for (k, v) in state.caches],
            kv_lens=state.kv_lens,
            last_logits=state.last_logits[beam_idx])
    # The final selected tokens are never consumed — no trailing step.

    best = int(jnp.argmax(scores))
    return jnp.asarray(seqs[best][None], jnp.int32), float(scores[best])


def beam_search_paged(gen: Generator, params, prompt, n_new: int, *,
                      num_beams: int = 4, page_size: int = 16,
                      stats: dict | None = None):
    """:func:`beam_search` over shared paged KV blocks: the prompt's
    pages are written ONCE and mapped read-only into every beam's block
    table; beams copy-on-write only the page they actually diverge in.

    Identical search to :func:`beam_search` (same expansion, scoring,
    and reorder rule — the paged decode forward computes the same layer
    math as ``Generator.step``), returning the same ``(tokens [1,
    n_new], score)``.  What changes is memory: prompt KV is held once —
    refcounted, not replicated — so wide beams over long prompts stop
    paying ``num_beams ×`` prompt cache (the ``test_beam.py`` paged
    tests pin both the oracle equality and the block accounting).

    World-1, float KV (the paged decode kernel's envelope — the
    contiguous :func:`beam_search` remains the SP / int8 path)."""
    from triton_dist_tpu.serve.block_manager import BlockManager
    from triton_dist_tpu.serve.engine import (
        _copy_pool_block,
        _fill_pool_pages,
        _paged_decode_forward,
    )

    assert prompt.shape[0] == 1, "beam search takes a single prompt"
    assert gen.attn.world == 1, "paged beams are world-1 (block tables)"
    assert not gen.attn.quantized, "paged beams need float KV pools"
    B = num_beams
    cfg = gen.cfg
    page = int(page_size)
    S0 = int(prompt.shape[1])
    total = S0 + n_new
    assert total <= gen.max_seq, "prompt + n_new exceeds max_seq"
    n_pages = -(-total // page)
    prompt_pages = -(-S0 // page)
    full_prompt = S0 // page             # pages every beam shares forever
    # Pool budget: the shared prompt + each beam's own suffix pages + one
    # transient block per beam for the in-flight copy-on-write split.
    num_blocks = 1 + prompt_pages + B * (n_pages - full_prompt + 1)
    bm = BlockManager(num_blocks, page)
    pools = [
        (jnp.zeros((num_blocks, cfg.n_kv_heads, page, cfg.head_dim),
                   cfg.dtype),
         jnp.zeros((num_blocks, cfg.n_kv_heads, page, cfg.head_dim),
                   cfg.dtype))
        for _ in range(cfg.n_layers)]

    # Prefill ONCE; scatter the prompt K/V into its pool pages, then map
    # those pages into every beam's table (refcount = num_beams — the
    # physical replication beam_search pays is gone).
    s1 = gen.prefill(params, prompt)
    fill = jax.jit(functools.partial(_fill_pool_pages, page=page),
                   donate_argnums=(0,))
    scratch = [(k[:, :, :prompt_pages * page, :],
                v[:, :, :prompt_pages * page, :]) for k, v in s1.caches]
    prefix = bm.allocate("__prefix__", S0)
    pools = fill(pools, scratch, jnp.asarray(np.asarray(prefix, np.int32)))
    beams = [f"beam{b}" for b in range(B)]
    for rid in beams:
        bm.share(rid, prefix)
    bm.free("__prefix__")                # beams now hold the only refs

    impl = gen.attn.ctx.impl
    interpret = gen.attn.ctx.interpret
    decode = jax.jit(functools.partial(
        _paged_decode_forward, cfg=cfg, page=page, impl=impl,
        interpret=interpret), donate_argnums=(1,))
    cow_copy = jax.jit(_copy_pool_block, donate_argnums=(0,))
    active = jnp.ones((B,), bool)

    def tables_now():
        t = np.zeros((B, n_pages), np.int32)
        for b, rid in enumerate(beams):
            row = bm.table(rid)
            t[b, :len(row)] = row
        return jnp.asarray(t)

    def make_writable(pools, pos):
        """Every beam must own the page ``pos`` writes: extend tables to
        cover it and split any still-shared page (the divergence COW —
        fires for the partially-filled prompt tail on the first step and
        for the reorder-shared tail after every reorder)."""
        for rid in beams:
            bm.ensure(rid, pos + 1)
            logical = pos // page
            if bm.ref_of(bm.table(rid)[logical]) > 1:
                old, new = bm.cow(rid, logical)
                pools = cow_copy(pools, jnp.int32(old), jnp.int32(new))
        return pools

    logprobs = jax.nn.log_softmax(s1.last_logits, axis=-1)   # [1, V]
    V = logprobs.shape[-1]
    first = jax.lax.top_k(logprobs[0], B)
    scores = first[0]
    seqs = np.asarray(first[1]).reshape(B, 1)
    token = first[1].astype(jnp.int32)                       # [B]
    kv_lens = jnp.full((B,), S0, jnp.int32)
    peak_used = num_blocks - 1 - bm.num_free

    for step in range(n_new - 1):
        pos = S0 + step
        pools = make_writable(pools, pos)
        peak_used = max(peak_used, num_blocks - 1 - bm.num_free)
        pools, logits = decode(params, pools, tables_now(), kv_lens,
                               token, active)
        kv_lens = kv_lens + 1
        logprobs = jax.nn.log_softmax(logits, axis=-1)       # [B, V]
        total_lp = scores[:, None] + logprobs
        top = jax.lax.top_k(total_lp.reshape(-1), B)
        scores = top[0]
        beam_idx = (top[1] // V).astype(jnp.int32)
        token = (top[1] % V).astype(jnp.int32)
        bi = np.asarray(beam_idx)
        seqs = np.concatenate([seqs[bi], np.asarray(token)[:, None]],
                              axis=1)
        # Reorder = TABLE remap, not a cache gather: each child shares
        # its parent's pages (surviving divergent pages stay where they
        # are; dead beams' pages free), and the next make_writable
        # splits only the tail page the children will write.
        new_tables = [bm.table(beams[int(bi[i])]) for i in range(B)]
        for rid in beams:
            bm.free(rid)
        for rid, tab in zip(beams, new_tables):
            bm.share(rid, tab)
    # The final selected tokens are never consumed — no trailing step.

    if stats is not None:
        stats.update(num_blocks=num_blocks, peak_used=peak_used,
                     cow_copies=bm.cow_copies,
                     shared_prompt_pages=full_prompt)
    best = int(jnp.argmax(scores))
    return jnp.asarray(seqs[best][None], jnp.int32), float(scores[best])
