"""Beam search over the sequence-parallel KV cache.

Serving-side addition beyond the reference.  Beams ride the generator's
batch dimension: prefill replicates the prompt per beam, every step scores
all beams in one batched decode, and the top ``num_beams`` (sequence,
continuation) pairs survive.  Beam reordering gathers the KV caches along
the batch axis — a [beams, H, S, D] take per layer, which XLA fuses with
the step's cache update.

Scoring is the standard sum of token log-probs (no length normalization —
see ``beam_search``'s docstring for why the knob is deliberately absent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.generate import GenerationState, Generator


def _map_cache(cache, fn):
    """Apply ``fn`` to one cache's arrays (float array or int8 dict)."""
    if isinstance(cache, dict):
        return {"q": fn(cache["q"]), "s": fn(cache["s"])}
    return fn(cache)


def beam_search(gen: Generator, params, prompt, n_new: int, *,
                num_beams: int = 4):
    """Beam-decode ``n_new`` tokens for ``prompt`` [1, S0].

    Returns (tokens [1, n_new] — the best beam's continuation, score
    float — its total log-prob).  All beams have the same length (no EOS
    handling), so GNMT-style length normalization would not change the
    winner and is deliberately not offered.
    """
    assert prompt.shape[0] == 1, "beam search takes a single prompt"
    B = num_beams
    # Prefill ONCE; replicate the resulting caches/logits per beam (the
    # beams only diverge from the first generated token on).
    s1 = gen.prefill(params, prompt)
    rep = lambda a: jnp.repeat(a, B, axis=0)  # noqa: E731
    state = GenerationState(
        caches=[(_map_cache(k, rep), _map_cache(v, rep))
                for (k, v) in s1.caches],
        kv_lens=rep(s1.kv_lens),
        last_logits=rep(s1.last_logits))

    logprobs = jax.nn.log_softmax(state.last_logits, axis=-1)  # [B, V]
    V = logprobs.shape[-1]
    # First expansion: all beams are identical — expand from beam 0 only.
    first = jax.lax.top_k(logprobs[0], B)
    scores = first[0]                                  # [B]
    seqs = np.asarray(first[1]).reshape(B, 1)          # [B, 1] host-side
    token = first[1].astype(jnp.int32)                 # [B]

    for _step in range(n_new - 1):
        state = gen.step(params, state, token)
        logprobs = jax.nn.log_softmax(state.last_logits, axis=-1)
        total = scores[:, None] + logprobs               # [B, V]
        top = jax.lax.top_k(total.reshape(-1), B)
        scores = top[0]
        beam_idx = (top[1] // V).astype(jnp.int32)       # [B]
        token = (top[1] % V).astype(jnp.int32)
        # Reorder host-side sequences and device-side caches by beam.
        bi = np.asarray(beam_idx)
        seqs = np.concatenate([seqs[bi], np.asarray(token)[:, None]],
                              axis=1)
        take = lambda a: a[beam_idx]  # noqa: E731
        state = GenerationState(
            caches=[(_map_cache(k, take), _map_cache(v, take))
                    for (k, v) in state.caches],
            kv_lens=state.kv_lens,
            last_logits=state.last_logits[beam_idx])
    # The final selected tokens are never consumed — no trailing step.

    best = int(jnp.argmax(scores))
    return jnp.asarray(seqs[best][None], jnp.int32), float(scores[best])
