"""Speculative decoding: a draft model proposes, the target verifies.

Serving-side addition beyond the reference (its decode story ends at the
attention kernel).  The classic recipe (Leviathan et al. / Chen et al.):
a small draft model autoregressively proposes ``k`` tokens; the target
model scores all ``k`` in ONE chunk forward over its KV cache
(models/generate.py ``_chunk_forward`` — the same machinery as chunked
prefill); proposals are accepted left to right, plus one bonus token.

One round loop (:class:`_SpeculativeBase`) with two verify strategies:
- :class:`SpeculativeGenerator` — greedy: accept while the proposal
  matches the target argmax.  Output is bit-identical to the target's
  own greedy decode.
- :class:`SpeculativeSampler` — stochastic rejection sampling: accept
  proposal ``x`` with prob ``min(1, π(x)/ρ(x))`` (π target, ρ draft,
  both post temperature/top-k/top-p), resample the first rejection from
  the residual ``normalize(max(π - ρ, 0))``.  The emitted distribution
  equals direct sampling from the target (:func:`speculative_accept_step`
  carries the per-step math; its distributional correctness is unit
  tested by Monte Carlo).

Cache handling is rollback-by-length: the verify chunk writes all ``k``
rows into the target cache, and rejected rows are simply left beyond
``kv_lens`` (decode attention masks by length; later writes overwrite
them).  Same for the draft's own cache.

v1 scope: batch size 1 (per-row accept counts diverge the chunk prefix).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.generate import GenerationState, Generator
from triton_dist_tpu.models.sampling import filtered_probs


def _greedy(logits) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@jax.jit
def speculative_accept_chain(pis, rhos, proposals, bonus_pi, key):
    """Whole-round accept chain ON DEVICE: one lax.scan over the k
    (pi, rho, proposal) triples + the bonus draw — so a round costs ONE
    [k+1]-token transfer instead of k+1 per-token host syncs (the
    round-1 advisor's latency finding).

    pis [k, V]: target dist at each position (pis[0] from the pre-round
    logits); rhos [k, V]; proposals [k] i32; bonus_pi [V]: target dist
    after all k.  Returns (m, tokens [k+1]) where m is the accept count
    and tokens[:m+1] is the round's emission (accepted prefix, then the
    residual sample at the first rejection — or the bonus when all k
    accepted).  Marginally the stream ~ target sampling (the per-step
    identity of :func:`speculative_accept_step`, applied left to right).
    """
    k = proposals.shape[0]
    keys = jax.random.split(key, k + 1)

    def step(alive, inp):
        pi, rho, prop, kk = inp
        accepted, token = speculative_accept_step(pi, rho, prop, kk)
        return jnp.logical_and(alive, accepted), (
            token, jnp.logical_and(alive, accepted))

    _, (tokens, acc) = jax.lax.scan(
        step, jnp.bool_(True), (pis, rhos, proposals, keys[:k]))
    m = jnp.sum(acc.astype(jnp.int32))
    bonus = jax.random.categorical(
        keys[k], jnp.log(bonus_pi + 1e-30)).astype(jnp.int32)
    # Position m holds the residual sample when m < k (the rejecting
    # step's token); when m == k the bonus closes the round.
    return m, jnp.concatenate([tokens, bonus[None]])


@jax.jit
def greedy_accept_chain(proposals, st_logits, logits_all):
    """Greedy accept ON DEVICE — the B=1 view of
    :func:`greedy_accept_chain_batched` (ONE accept rule, two shapes):
    proposals [k], st_logits [1, V], logits_all [1, k, V]; returns
    (m scalar, toks [k+1])."""
    m, toks = greedy_accept_chain_batched(proposals[None], st_logits,
                                          logits_all)
    return m[0], toks[0]


@jax.jit
def greedy_accept_chain_batched(proposals, st_logits, logits_all):
    """Per-row greedy accept (r5 batched verify): proposals [B, k],
    st_logits [B, V] (pre-round), logits_all [B, k, V].  Returns
    (m [B], toks [B, k+1]) — row b emits toks[b, :m[b]+1]."""
    B, k = proposals.shape
    expected = jnp.concatenate(
        [_greedy(st_logits)[:, None], _greedy(logits_all)], axis=1)
    matches = (proposals == expected[:, :k]).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)     # [B]
    ext = jnp.concatenate([proposals, proposals[:, -1:]], axis=1)
    toks = jnp.where(jnp.arange(k + 1)[None] == m[:, None], expected, ext)
    return m, toks


def accept_chain_rowwise(proposals, expected, k_rows) -> jax.Array:
    """Per-row accept count for the serving engine's FUSED speculative
    round (``serve/engine._spec_round_fused``): ``proposals`` [B, K] are
    the draft's guesses, ``expected`` [B, K+1] are the TARGET'S OWN
    next-token choices at the same emission indices (greedy argmax, or
    the seeded ``sampling.sample_positions_rowwise`` draw — the exact
    stream ``_choose_token`` / the decode horizon would emit), and
    ``k_rows`` [B] is each row's speculation budget this round (adaptive
    k: positions ``>= k_rows[b]`` auto-reject).

    Returns ``m`` [B]: the longest prefix with ``proposals[b, :m] ==
    expected[b, :m]``.  The round emits ``expected[b, :m+1]`` — every
    emitted token is the target's own choice, so the emitted stream is
    DEFINITIONALLY the target's greedy/seeded stream (bit-identical to
    serving without a draft); speculation only changes how many of those
    tokens commit per dispatch.  For sampled rows this is rejection
    sampling under shared randomness: draft and target draw their token
    at emission index ``i`` from the SAME ``fold_in(key(seed), i)`` key,
    so when the draft's filtered distribution tracks the target's, the
    coupled draws coincide with high probability and long chains accept
    — while a token that differs is replaced by the target's own draw,
    never resampled from a residual (which would fork the stream from
    the no-draft engine).  Truncating the chain (per-row budget, page
    capacity) keeps validity for free: any prefix of the target's own
    stream is still the target's stream."""
    K = proposals.shape[1]
    pos = jnp.arange(K, dtype=jnp.int32)[None]
    ok = ((proposals == expected[:, :K])
          & (pos < k_rows[:, None])).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(ok, axis=1), axis=1)


@jax.jit
def speculative_accept_step(pi, rho, proposal, key):
    """One rejection-sampling step.  pi/rho [V] (target/draft sampling
    distributions), proposal scalar int32 drawn from rho.

    Returns (accepted bool, token int32): accept the proposal with
    probability ``min(1, pi/rho)``; otherwise draw from the residual
    ``normalize(max(pi - rho, 0))``.  Marginally, token ~ pi — the
    standard speculative-sampling identity.
    """
    k1, k2 = jax.random.split(key)
    ratio = pi[proposal] / jnp.maximum(rho[proposal], 1e-20)
    accepted = jax.random.uniform(k1) < jnp.minimum(ratio, 1.0)
    residual = jnp.maximum(pi - rho, 0.0)
    total = jnp.sum(residual)
    # Degenerate residual (rho covers pi): acceptance is then certain;
    # the fallback to pi just keeps categorical well-defined.
    residual = jnp.where(total > 0, residual / jnp.maximum(total, 1e-20),
                         pi)
    alt = jax.random.categorical(k2, jnp.log(residual + 1e-30))
    token = jnp.where(accepted, proposal, alt).astype(jnp.int32)
    return accepted, token


class _SpeculativeBase:
    """Shared round loop; subclasses supply propose / verify / fallback.

    Strategy contract (batch-1; ``key`` may be None for deterministic
    strategies and is threaded through otherwise):
    - ``_propose(d_params, sd, k, key) -> (proposals [k ints], aux, sd,
      key)`` — draft k tokens, consuming them into the draft cache.
    - ``_verify(st_logits, logits_all, proposals, aux, key) ->
      (m, emitted, key)`` — accept count ``m`` and the FULL list of
      tokens this round emits (accepted prefix + the round-closing
      token, which is consumed via a regular step next).
    - ``_fallback(logits, key) -> (token int, key)`` — one plain target
      token when there is no cache headroom to speculate.
    """

    def __init__(self, target: Generator, draft: Generator, k: int = 4):
        assert target.cfg.vocab == draft.cfg.vocab, "vocabularies differ"
        self.target = target
        self.draft = draft
        self.k = int(k)

    def generate(self, t_params, d_params, prompt, n_new: int, key=None):
        """Decode ``n_new`` tokens for ``prompt`` [B, S0].  Returns
        (tokens [B, n_new], stats with target_passes / accept_rate).

        B > 1 (r5): per-row accept counts diverge the cache lengths; the
        batched verify pass scores every row's k drafts against its OWN
        length in one multi-token decode call (`generate._verify_forward`
        + the q_lens kernel).  Both strategies: greedy stays bit-exact
        per row; rejection sampling vmaps the accept chain with per-row
        subkeys.  World-1 float caches; batch-1 keeps full SP + int8."""
        if prompt.shape[0] > 1:
            return self._generate_batched(t_params, d_params, prompt,
                                          n_new, key)
        st = self.target.prefill(t_params, prompt)
        sd = self.draft.prefill(d_params, prompt)

        out: list[int] = []
        n_target_passes = 0
        n_proposed = 0
        n_accepted = 0
        while len(out) < n_new:
            L = int(st.kv_lens[0])
            k = min(self.k, self.target.max_seq - 1 - L,
                    self.draft.max_seq - 1 - int(sd.kv_lens[0]))
            if k <= 0:
                # No headroom to speculate (last cache slots): plain
                # target steps — this must never under-serve
                # Generator.generate.
                token, key = self._fallback(st.last_logits, key)
                out.append(token)
                if len(out) < n_new:
                    st = self.target.step(t_params, st,
                                          jnp.asarray([token], jnp.int32))
                    n_target_passes += 1
                continue

            # 1. Draft proposes k tokens (consuming them).
            proposals, aux, sd, key = self._propose(d_params, sd, k, key)
            n_proposed += k

            # 2. Target scores all k in one chunk forward.
            chunk = jnp.asarray([proposals], jnp.int32)
            new_caches, logits_all = self.target._chunk_jit(
                t_params, chunk, st.caches, jnp.int32(L),
                quantized=self.target.attn.quantized)
            n_target_passes += 1

            # 3. Strategy-specific accept + round-closing token.
            m, emitted, key = self._verify(st.last_logits, logits_all,
                                           proposals, aux, key)
            n_accepted += m
            out.extend(emitted)

            # 4. Roll both models to the accepted length; consume the
            # round-closing token via a regular decode step.
            closing = jnp.asarray([emitted[-1]], jnp.int32)
            st = GenerationState(
                caches=new_caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=(st.last_logits if m == 0
                             else logits_all[:, m - 1]))
            st = self.target.step(t_params, st, closing)
            sd = GenerationState(
                caches=sd.caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=sd.last_logits)  # stale; refreshed by step
            sd = self.draft.step(d_params, sd, closing)

        tokens = jnp.asarray([out[:n_new]], jnp.int32)
        stats = {
            "target_passes": n_target_passes,
            "proposed": n_proposed,
            "accepted": n_accepted,
            "accept_rate": n_accepted / max(n_proposed, 1),
        }
        return tokens, stats

    # -- batched (B > 1) strategy hooks --------------------------------
    # Contract mirrors the batch-1 one, row-vectorized:
    # - ``_propose_batched(d_params, sd, k, key, active) ->
    #   (proposals [B, k], aux, sd, key)`` — ``active`` [B] bool rides
    #   into the draft steps so frozen rows' caches stay frozen
    # - ``_verify_batched(st_logits [B, V], logits_all [B, k, V],
    #   proposals, aux, key) -> (m [B] device, toks [B, k+1] device,
    #   key)`` — row b emits toks[b, :m_b+1]
    # - ``_fallback_batched(logits [B, V], key) -> (tokens [B], key)``

    def _propose_batched(self, d_params, sd, k, key, active=None):
        raise NotImplementedError

    def _verify_batched(self, st_logits, logits_all, proposals, aux, key):
        raise NotImplementedError

    def _fallback_batched(self, logits, key):
        raise NotImplementedError

    def _generate_batched(self, t_params, d_params, prompt, n_new, key):
        """Batched speculative loop (r5): rows propose in lockstep, ONE
        multi-token verify pass (`generate._verify_forward` + the q_lens
        decode kernel) scores all rows against their own (diverging)
        cache lengths, accepts apply per row."""
        tgt, drf = self.target, self.draft
        assert tgt.attn.world == 1 and drf.attn.world == 1, (
            "batched speculative verify is world-1 (batch-1 keeps SP)")
        assert not tgt.attn.quantized, (
            "batched speculative verify needs a float target cache")
        B = prompt.shape[0]
        st = tgt.prefill(t_params, prompt)
        sd = drf.prefill(d_params, prompt)
        verify = tgt._verify_jit  # cached on the Generator (no
        # per-call recompile; carries the Generator's impl + ffn hook)

        out = [[] for _ in range(B)]
        n_target_passes = n_proposed = n_accepted = 0
        draft_dead = False  # latched when the draft-step skip fires
        while min(len(o) for o in out) < n_new:
            # Per-row RETIREMENT: finished rows freeze (cache length
            # stops advancing, emissions stop) so a fast row cannot
            # overflow a cache provisioned for exactly n_new while the
            # lockstep loop waits on a slow row; active rows' emissions
            # clamp to their remaining room for the same reason —
            # emitted tokens and consumed cache slots stay 1:1 per row.
            room = np.array([n_new - len(o) for o in out])
            act_np = room > 0
            active = jnp.asarray(act_np)
            n_act = int(act_np.sum())
            top = int(jnp.max(jnp.where(active, st.kv_lens, -1)))
            k = min(self.k, tgt.max_seq - 1 - top,
                    drf.max_seq - 1
                    - int(jnp.max(jnp.where(active, sd.kv_lens, -1))))
            if draft_dead:
                # Once the draft-step skip has fired the draft cache is
                # behind the emitted stream; retiring the row that pinned
                # the draft at max_seq can re-open k > 0 here, but
                # resuming would overwrite sd.kv_lens with the target
                # length and propose over uninitialized K/V rows (ADVICE
                # r5 finding #3).  Speculation stays off for the rest of
                # the call.
                k = 0
            if k <= 0:
                token, key = self._fallback_batched(st.last_logits, key)
                for b, t in enumerate(np.asarray(token)):
                    if act_np[b]:
                        out[b].append(int(t))
                if min(len(o) for o in out) < n_new:
                    st = tgt.step(t_params, st, token, active=active)
                    # Keep the DRAFT in lockstep too: retirement can
                    # re-open speculation (a fast row freezing drops the
                    # active top), and a draft that missed the fallback
                    # tokens would propose from stale state — the accept
                    # rate silently collapses.  Skip only when the draft
                    # itself has no headroom — and LATCH the skip: from
                    # that point the draft cache is permanently behind,
                    # so ``draft_dead`` pins k = 0 above and speculation
                    # never resumes (re-opening it after a retirement
                    # would propose over uninitialized K/V).
                    if (not draft_dead
                            and int(jnp.max(jnp.where(active, sd.kv_lens,
                                                      -1))) < drf.max_seq):
                        sd = drf.step(d_params, sd, token, active=active)
                    else:
                        draft_dead = True
                    n_target_passes += 1
                continue

            # 1. Draft proposes k tokens for every row (its cache and
            # lengths advance per row; frozen rows' drafts are ignored
            # and rolled back below).
            proposals, aux, sd, key = self._propose_batched(
                d_params, sd, k, key, active)
            n_proposed += n_act * k

            # 2. ONE batched verify pass at per-row lengths.
            L = st.kv_lens
            new_caches, logits_all = verify(t_params, proposals,
                                            st.caches, L)
            n_target_passes += 1

            # 3. Per-row accept, clamped to each row's remaining room
            # (the emitted prefix of the accept chain stays valid under
            # truncation: every kept token was accepted).
            m_dev, toks, key = self._verify_batched(
                st.last_logits, logits_all, proposals, aux, key)
            m_np, toks_np = jax.device_get((m_dev, toks))
            m_used = np.where(act_np,
                              np.minimum(np.asarray(m_np), room - 1), 0)
            for b in range(B):
                if act_np[b]:
                    out[b].extend(int(t) for t in
                                  toks_np[b, :int(m_used[b]) + 1])
            # Stats count RAW accepts (draft quality); emission/cache use
            # the room-clamped m_used.
            n_accepted += int(np.where(act_np, np.asarray(m_np), 0).sum())

            # 4. Roll both models to the per-row accepted lengths
            # (frozen rows roll back fully) and consume each active
            # row's round-closing token via a frozen-aware step.
            m_used_dev = jnp.asarray(m_used.astype(np.int32))
            closing = jnp.take_along_axis(
                toks, m_used_dev[:, None], axis=1)[:, 0]  # [B]
            st = GenerationState(caches=new_caches,
                                 kv_lens=L + m_used_dev,
                                 last_logits=st.last_logits)  # stale;
            # refreshed by the step below (never read in between)
            st = tgt.step(t_params, st, closing, active=active)
            sd = GenerationState(caches=sd.caches,
                                 kv_lens=L + m_used_dev,
                                 last_logits=sd.last_logits)  # stale too
            sd = drf.step(d_params, sd, closing, active=active)

        tokens = jnp.asarray([o[:n_new] for o in out], jnp.int32)
        stats = {
            "target_passes": n_target_passes,
            "proposed": n_proposed,
            "accepted": n_accepted,
            "accept_rate": n_accepted / max(n_proposed, 1),
        }
        return tokens, stats


class SpeculativeGenerator(_SpeculativeBase):
    """Greedy verifier: output is bit-identical to the target's greedy
    decode; the draft only changes how many target passes are needed
    (up to k+1 tokens per pass when the draft agrees)."""

    def _propose_batched(self, d_params, sd, k, key, active=None):
        props = []
        for _ in range(k):
            tok = _greedy(sd.last_logits)                 # [B]
            sd = self.draft.step(d_params, sd, tok, active=active)
            props.append(tok)
        return jnp.stack(props, axis=1), None, sd, key

    def _verify_batched(self, st_logits, logits_all, proposals, aux, key):
        m_dev, toks = greedy_accept_chain_batched(
            proposals, st_logits, logits_all)
        return m_dev, toks, key

    def _fallback_batched(self, logits, key):
        return _greedy(logits), key

    def _propose(self, d_params, sd, k, key):
        # The B=1 view of the batched propose loop (one loop, two shapes).
        proposals, aux, sd, key = self._propose_batched(d_params, sd, k,
                                                        key)
        return proposals[0], aux, sd, key

    def _verify(self, st_logits, logits_all, proposals, aux, key):
        m_dev, toks = greedy_accept_chain(proposals, st_logits, logits_all)
        m, toks = jax.device_get((m_dev, toks))  # one round-trip
        return int(m), [int(t) for t in toks[:int(m) + 1]], key

    def _fallback(self, logits, key):
        return int(_greedy(logits)[0]), key


class SpeculativeSampler(_SpeculativeBase):
    """Rejection-sampling verifier: the emitted stream is distributed
    exactly as direct target sampling with the same temperature/top-k/
    top-p knobs (``generate`` requires a PRNG ``key``)."""

    def __init__(self, target: Generator, draft: Generator, k: int = 4, *,
                 temperature: float = 1.0, top_k=None, top_p=None):
        assert temperature > 0, "use SpeculativeGenerator for greedy"
        super().__init__(target, draft, k)
        self._probs = functools.partial(
            filtered_probs, temperature=temperature, top_k=top_k,
            top_p=top_p)

    def _draw(self, pi, key):
        key, sub = jax.random.split(key)
        return int(jax.random.categorical(sub, jnp.log(pi + 1e-30))), key

    def _propose_batched(self, d_params, sd, k, key, active=None):
        props, rhos = [], []
        for _ in range(k):
            rho = self._probs(sd.last_logits)             # [B, V]
            key, sub = jax.random.split(key)
            tok = jax.random.categorical(
                sub, jnp.log(rho + 1e-30)).astype(jnp.int32)  # [B]
            rhos.append(rho)
            sd = self.draft.step(d_params, sd, tok, active=active)
            props.append(tok)
        return (jnp.stack(props, axis=1),                 # [B, k]
                jnp.stack(rhos, axis=1), sd, key)         # [B, k, V]

    def _verify_batched(self, st_logits, logits_all, proposals, rhos, key):
        # Per-row rejection sampling: the batch-1 accept chain vmapped
        # over rows with independent subkeys — each row's emitted stream
        # keeps the exact target-sampling distribution (the per-step
        # identity is row-local).
        B, k = proposals.shape
        all_pi = self._probs(jnp.concatenate(
            [st_logits[:, None], logits_all], axis=1))    # [B, k+1, V]
        pis, bonus_pi = all_pi[:, :k], all_pi[:, k]
        key, sub = jax.random.split(key)
        row_keys = jax.random.split(sub, B)
        m, toks = jax.vmap(speculative_accept_chain)(
            pis, rhos, proposals, bonus_pi, row_keys)
        return m, toks, key

    def _fallback_batched(self, logits, key):
        key, sub = jax.random.split(key)
        tok = jax.random.categorical(
            sub, jnp.log(self._probs(logits) + 1e-30)).astype(jnp.int32)
        return tok, key

    def _propose(self, d_params, sd, k, key):
        # The B=1 view of the batched propose loop (one loop, two shapes).
        proposals, rhos, sd, key = self._propose_batched(d_params, sd, k,
                                                         key)
        return proposals[0], rhos[0], sd, key

    def _verify(self, st_logits, logits_all, proposals, rhos, key):
        # Whole-round accept chain on device (speculative_accept_chain):
        # ONE [k+1]-token fetch per round instead of one sync per token.
        # filtered_probs is batched: one vectorized call covers all k
        # positions plus the bonus distribution.
        k = proposals.shape[0]
        all_pi = self._probs(jnp.concatenate([st_logits, logits_all[0]]))
        pis, bonus_pi = all_pi[:k], all_pi[k]
        key, sub = jax.random.split(key)
        m_dev, toks = speculative_accept_chain(pis, rhos, proposals,
                                               bonus_pi, sub)
        m, toks = jax.device_get((m_dev, toks))  # one round-trip
        return int(m), [int(t) for t in toks[:int(m) + 1]], key

    def _fallback(self, logits, key):
        return self._draw(self._probs(logits[0]), key)
