"""Speculative decoding: a draft model proposes, the target verifies.

Serving-side addition beyond the reference (its decode story ends at the
attention kernel).  The classic recipe (Leviathan et al. / Chen et al.):
a small draft model autoregressively proposes ``k`` tokens; the target
model scores all ``k`` in ONE chunk forward over its KV cache
(models/generate.py ``_chunk_forward`` — the same machinery as chunked
prefill); proposals are accepted left to right, plus one bonus token.

One round loop (:class:`_SpeculativeBase`) with two verify strategies:
- :class:`SpeculativeGenerator` — greedy: accept while the proposal
  matches the target argmax.  Output is bit-identical to the target's
  own greedy decode.
- :class:`SpeculativeSampler` — stochastic rejection sampling: accept
  proposal ``x`` with prob ``min(1, π(x)/ρ(x))`` (π target, ρ draft,
  both post temperature/top-k/top-p), resample the first rejection from
  the residual ``normalize(max(π - ρ, 0))``.  The emitted distribution
  equals direct sampling from the target (:func:`speculative_accept_step`
  carries the per-step math; its distributional correctness is unit
  tested by Monte Carlo).

Cache handling is rollback-by-length: the verify chunk writes all ``k``
rows into the target cache, and rejected rows are simply left beyond
``kv_lens`` (decode attention masks by length; later writes overwrite
them).  Same for the draft's own cache.

v1 scope: batch size 1 (per-row accept counts diverge the chunk prefix).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.generate import GenerationState, Generator
from triton_dist_tpu.models.sampling import filtered_probs


def _greedy(logits) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@jax.jit
def speculative_accept_chain(pis, rhos, proposals, bonus_pi, key):
    """Whole-round accept chain ON DEVICE: one lax.scan over the k
    (pi, rho, proposal) triples + the bonus draw — so a round costs ONE
    [k+1]-token transfer instead of k+1 per-token host syncs (the
    round-1 advisor's latency finding).

    pis [k, V]: target dist at each position (pis[0] from the pre-round
    logits); rhos [k, V]; proposals [k] i32; bonus_pi [V]: target dist
    after all k.  Returns (m, tokens [k+1]) where m is the accept count
    and tokens[:m+1] is the round's emission (accepted prefix, then the
    residual sample at the first rejection — or the bonus when all k
    accepted).  Marginally the stream ~ target sampling (the per-step
    identity of :func:`speculative_accept_step`, applied left to right).
    """
    k = proposals.shape[0]
    keys = jax.random.split(key, k + 1)

    def step(alive, inp):
        pi, rho, prop, kk = inp
        accepted, token = speculative_accept_step(pi, rho, prop, kk)
        return jnp.logical_and(alive, accepted), (
            token, jnp.logical_and(alive, accepted))

    _, (tokens, acc) = jax.lax.scan(
        step, jnp.bool_(True), (pis, rhos, proposals, keys[:k]))
    m = jnp.sum(acc.astype(jnp.int32))
    bonus = jax.random.categorical(
        keys[k], jnp.log(bonus_pi + 1e-30)).astype(jnp.int32)
    # Position m holds the residual sample when m < k (the rejecting
    # step's token); when m == k the bonus closes the round.
    return m, jnp.concatenate([tokens, bonus[None]])


@jax.jit
def greedy_accept_chain(proposals, st_logits, logits_all):
    """Greedy accept ON DEVICE: expected[i] is the target argmax at
    position i (independent of acceptance), m = length of the matching
    prefix, tokens[:m+1] = accepted prefix + the correct greedy token at
    position m.  One transfer per round, bit-identical to the host loop.
    """
    k = proposals.shape[0]
    expected = jnp.concatenate([
        _greedy(st_logits),                       # position 0
        _greedy(logits_all[0, :k]),               # positions 1..k
    ])                                            # [k+1]
    matches = (proposals == expected[:k]).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(matches))
    toks = jnp.where(jnp.arange(k + 1) == m, expected,
                     jnp.concatenate([proposals, proposals[-1:]]))
    return m, toks


@jax.jit
def speculative_accept_step(pi, rho, proposal, key):
    """One rejection-sampling step.  pi/rho [V] (target/draft sampling
    distributions), proposal scalar int32 drawn from rho.

    Returns (accepted bool, token int32): accept the proposal with
    probability ``min(1, pi/rho)``; otherwise draw from the residual
    ``normalize(max(pi - rho, 0))``.  Marginally, token ~ pi — the
    standard speculative-sampling identity.
    """
    k1, k2 = jax.random.split(key)
    ratio = pi[proposal] / jnp.maximum(rho[proposal], 1e-20)
    accepted = jax.random.uniform(k1) < jnp.minimum(ratio, 1.0)
    residual = jnp.maximum(pi - rho, 0.0)
    total = jnp.sum(residual)
    # Degenerate residual (rho covers pi): acceptance is then certain;
    # the fallback to pi just keeps categorical well-defined.
    residual = jnp.where(total > 0, residual / jnp.maximum(total, 1e-20),
                         pi)
    alt = jax.random.categorical(k2, jnp.log(residual + 1e-30))
    token = jnp.where(accepted, proposal, alt).astype(jnp.int32)
    return accepted, token


class _SpeculativeBase:
    """Shared round loop; subclasses supply propose / verify / fallback.

    Strategy contract (batch-1; ``key`` may be None for deterministic
    strategies and is threaded through otherwise):
    - ``_propose(d_params, sd, k, key) -> (proposals [k ints], aux, sd,
      key)`` — draft k tokens, consuming them into the draft cache.
    - ``_verify(st_logits, logits_all, proposals, aux, key) ->
      (m, emitted, key)`` — accept count ``m`` and the FULL list of
      tokens this round emits (accepted prefix + the round-closing
      token, which is consumed via a regular step next).
    - ``_fallback(logits, key) -> (token int, key)`` — one plain target
      token when there is no cache headroom to speculate.
    """

    def __init__(self, target: Generator, draft: Generator, k: int = 4):
        assert target.cfg.vocab == draft.cfg.vocab, "vocabularies differ"
        self.target = target
        self.draft = draft
        self.k = int(k)

    def generate(self, t_params, d_params, prompt, n_new: int, key=None):
        """Decode ``n_new`` tokens for ``prompt`` [1, S0].  Returns
        (tokens [1, n_new], stats with target_passes / accept_rate)."""
        assert prompt.shape[0] == 1, "speculative v1 is batch-1"
        st = self.target.prefill(t_params, prompt)
        sd = self.draft.prefill(d_params, prompt)

        out: list[int] = []
        n_target_passes = 0
        n_proposed = 0
        n_accepted = 0
        while len(out) < n_new:
            L = int(st.kv_lens[0])
            k = min(self.k, self.target.max_seq - 1 - L,
                    self.draft.max_seq - 1 - int(sd.kv_lens[0]))
            if k <= 0:
                # No headroom to speculate (last cache slots): plain
                # target steps — this must never under-serve
                # Generator.generate.
                token, key = self._fallback(st.last_logits, key)
                out.append(token)
                if len(out) < n_new:
                    st = self.target.step(t_params, st,
                                          jnp.asarray([token], jnp.int32))
                    n_target_passes += 1
                continue

            # 1. Draft proposes k tokens (consuming them).
            proposals, aux, sd, key = self._propose(d_params, sd, k, key)
            n_proposed += k

            # 2. Target scores all k in one chunk forward.
            chunk = jnp.asarray([proposals], jnp.int32)
            new_caches, logits_all = self.target._chunk_jit(
                t_params, chunk, st.caches, jnp.int32(L),
                quantized=self.target.attn.quantized)
            n_target_passes += 1

            # 3. Strategy-specific accept + round-closing token.
            m, emitted, key = self._verify(st.last_logits, logits_all,
                                           proposals, aux, key)
            n_accepted += m
            out.extend(emitted)

            # 4. Roll both models to the accepted length; consume the
            # round-closing token via a regular decode step.
            closing = jnp.asarray([emitted[-1]], jnp.int32)
            st = GenerationState(
                caches=new_caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=(st.last_logits if m == 0
                             else logits_all[:, m - 1]))
            st = self.target.step(t_params, st, closing)
            sd = GenerationState(
                caches=sd.caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=sd.last_logits)  # stale; refreshed by step
            sd = self.draft.step(d_params, sd, closing)

        tokens = jnp.asarray([out[:n_new]], jnp.int32)
        stats = {
            "target_passes": n_target_passes,
            "proposed": n_proposed,
            "accepted": n_accepted,
            "accept_rate": n_accepted / max(n_proposed, 1),
        }
        return tokens, stats


class SpeculativeGenerator(_SpeculativeBase):
    """Greedy verifier: output is bit-identical to the target's greedy
    decode; the draft only changes how many target passes are needed
    (up to k+1 tokens per pass when the draft agrees)."""

    def _propose(self, d_params, sd, k, key):
        proposals = []
        for _ in range(k):
            tok = _greedy(sd.last_logits)   # stays on device: no sync
            sd = self.draft.step(d_params, sd, tok)
            proposals.append(tok[0])
        return jnp.stack(proposals), None, sd, key

    def _verify(self, st_logits, logits_all, proposals, aux, key):
        m_dev, toks = greedy_accept_chain(proposals, st_logits, logits_all)
        m, toks = jax.device_get((m_dev, toks))  # one round-trip
        return int(m), [int(t) for t in toks[:int(m) + 1]], key

    def _fallback(self, logits, key):
        return int(_greedy(logits)[0]), key


class SpeculativeSampler(_SpeculativeBase):
    """Rejection-sampling verifier: the emitted stream is distributed
    exactly as direct target sampling with the same temperature/top-k/
    top-p knobs (``generate`` requires a PRNG ``key``)."""

    def __init__(self, target: Generator, draft: Generator, k: int = 4, *,
                 temperature: float = 1.0, top_k=None, top_p=None):
        assert temperature > 0, "use SpeculativeGenerator for greedy"
        super().__init__(target, draft, k)
        self._probs = functools.partial(
            filtered_probs, temperature=temperature, top_k=top_k,
            top_p=top_p)

    def _draw(self, pi, key):
        key, sub = jax.random.split(key)
        return int(jax.random.categorical(sub, jnp.log(pi + 1e-30))), key

    def _propose(self, d_params, sd, k, key):
        proposals, rhos = [], []
        for _ in range(k):
            rho = self._probs(sd.last_logits[0])          # [V]
            key, sub = jax.random.split(key)
            tok = jax.random.categorical(
                sub, jnp.log(rho + 1e-30)).astype(jnp.int32)
            rhos.append(rho)
            sd = self.draft.step(d_params, sd, tok[None])  # no host sync
            proposals.append(tok)
        return jnp.stack(proposals), jnp.stack(rhos), sd, key

    def _verify(self, st_logits, logits_all, proposals, rhos, key):
        # Whole-round accept chain on device (speculative_accept_chain):
        # ONE [k+1]-token fetch per round instead of one sync per token.
        # filtered_probs is batched: one vectorized call covers all k
        # positions plus the bonus distribution.
        k = proposals.shape[0]
        all_pi = self._probs(jnp.concatenate([st_logits, logits_all[0]]))
        pis, bonus_pi = all_pi[:k], all_pi[k]
        key, sub = jax.random.split(key)
        m_dev, toks = speculative_accept_chain(pis, rhos, proposals,
                                               bonus_pi, sub)
        m, toks = jax.device_get((m_dev, toks))  # one round-trip
        return int(m), [int(t) for t in toks[:int(m) + 1]], key

    def _fallback(self, logits, key):
        return self._draw(self._probs(logits[0]), key)
