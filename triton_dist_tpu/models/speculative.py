"""Speculative decoding: a draft model proposes, the target verifies.

Serving-side addition beyond the reference (its decode story ends at the
attention kernel).  The classic recipe (Leviathan et al. / Chen et al.):
a small draft model autoregressively proposes ``k`` tokens; the target
model scores all ``k`` in ONE chunk forward over its KV cache
(models/generate.py ``_chunk_forward`` — the same machinery as chunked
prefill); proposals are accepted left to right, plus one bonus token.

Two verifiers:
- :class:`SpeculativeGenerator` — greedy: accept while the proposal
  matches the target argmax.  Output is bit-identical to the target's
  own greedy decode.
- :class:`SpeculativeSampler` — stochastic rejection sampling: accept
  proposal ``x`` with prob ``min(1, π(x)/ρ(x))`` (π target, ρ draft,
  both post temperature/top-k/top-p), resample the first rejection from
  the residual ``normalize(max(π - ρ, 0))``.  The emitted distribution
  equals direct sampling from the target (:func:`speculative_accept_step`
  carries the per-step math; its distributional correctness is unit
  tested by Monte Carlo).

Cache handling is rollback-by-length: the verify chunk writes all ``k``
rows into the target cache, and rejected rows are simply left beyond
``kv_lens`` (decode attention masks by length; later writes overwrite
them).  Same for the draft's own cache.

v1 scope: batch size 1 (per-row accept counts diverge the chunk prefix).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.generate import GenerationState, Generator
from triton_dist_tpu.models.sampling import _apply_top_k, _apply_top_p


def _greedy(logits) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("temperature", "top_k", "top_p"))
def filtered_probs(logits, *, temperature: float, top_k=None, top_p=None):
    """logits [..., V] → the post-filter sampling distribution π [..., V]
    (what ``sampling.sample_logits`` draws from)."""
    x = logits.astype(jnp.float32) / temperature
    if top_k is not None and 0 < top_k < x.shape[-1]:
        x = _apply_top_k(x, top_k)
    if top_p is not None and top_p < 1.0:
        x = _apply_top_p(x, top_p)
    return jax.nn.softmax(x, axis=-1)


@jax.jit
def speculative_accept_step(pi, rho, proposal, key):
    """One rejection-sampling step.  pi/rho [V] (target/draft sampling
    distributions), proposal scalar int32 drawn from rho.

    Returns (accepted bool, token int32): accept the proposal with
    probability ``min(1, pi/rho)``; otherwise draw from the residual
    ``normalize(max(pi - rho, 0))``.  Marginally, token ~ pi — the
    standard speculative-sampling identity.
    """
    k1, k2 = jax.random.split(key)
    ratio = pi[proposal] / jnp.maximum(rho[proposal], 1e-20)
    accepted = jax.random.uniform(k1) < jnp.minimum(ratio, 1.0)
    residual = jnp.maximum(pi - rho, 0.0)
    total = jnp.sum(residual)
    # Degenerate residual (rho covers pi, ratio>=1 everywhere → accepted
    # is certain; the fallback to pi keeps categorical well-defined).
    residual = jnp.where(total > 0, residual / jnp.maximum(total, 1e-20),
                         pi)
    alt = jax.random.categorical(k2, jnp.log(residual + 1e-30))
    token = jnp.where(accepted, proposal, alt).astype(jnp.int32)
    return accepted, token


class SpeculativeGenerator:
    """Pairs a target and a draft :class:`Generator` (same tokenizer/vocab;
    the draft is typically a much smaller config)."""

    def __init__(self, target: Generator, draft: Generator, k: int = 4):
        assert target.cfg.vocab == draft.cfg.vocab, "vocabularies differ"
        self.target = target
        self.draft = draft
        self.k = int(k)

    def generate(self, t_params, d_params, prompt, n_new: int):
        """Greedy-decode ``n_new`` tokens for ``prompt`` [1, S0].

        Returns (tokens [1, n_new], stats dict with ``target_passes`` and
        ``accept_rate``) — tokens are bit-identical to
        ``target.generate(...)`` greedy output.
        """
        assert prompt.shape[0] == 1, "speculative v1 is batch-1"
        st = self.target.prefill(t_params, prompt)
        sd = self.draft.prefill(d_params, prompt)

        out: list[int] = []
        n_target_passes = 0
        n_proposed = 0
        n_accepted = 0
        while len(out) < n_new:
            L = int(st.kv_lens[0])
            k = min(self.k, self.target.max_seq - 1 - L,
                    self.draft.max_seq - 1 - int(sd.kv_lens[0]))
            if k <= 0:
                # No headroom to speculate (last cache slots): fall back
                # to plain greedy target steps — same behavior as
                # Generator.generate, which this must never under-serve.
                tok = _greedy(st.last_logits)
                out.append(int(tok[0]))
                if len(out) < n_new:
                    st = self.target.step(t_params, st, tok)
                    n_target_passes += 1
                continue

            # 1. Draft proposes k greedy tokens (consuming them).
            proposals = []
            for _ in range(k):
                tok = _greedy(sd.last_logits)
                sd = self.draft.step(d_params, sd, tok)
                proposals.append(int(tok[0]))
            n_proposed += k

            # 2. Target scores all k in one chunk forward.
            chunk = jnp.asarray([proposals], jnp.int32)
            new_caches, logits_all = self.target._chunk_jit(
                t_params, chunk, st.caches, jnp.int32(L),
                quantized=self.target.attn.quantized)
            n_target_passes += 1

            # 3. Accept the matching prefix; bonus token from the target.
            expected = int(_greedy(st.last_logits)[0])
            m = 0
            while m < k and proposals[m] == expected:
                out.append(proposals[m])
                m += 1
                expected = int(_greedy(logits_all[:, m - 1])[0])
            n_accepted += m
            bonus = expected  # the correct greedy token at position L+m
            out.append(bonus)

            # 4. Roll both models to the accepted length + consume bonus.
            st = GenerationState(
                caches=new_caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=(st.last_logits if m == 0
                             else logits_all[:, m - 1]))
            st = self.target.step(t_params, st,
                                  jnp.asarray([bonus], jnp.int32))
            sd = GenerationState(
                caches=sd.caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=sd.last_logits)  # stale; refreshed by step
            sd = self.draft.step(d_params, sd,
                                 jnp.asarray([bonus], jnp.int32))

        tokens = jnp.asarray([out[:n_new]], jnp.int32)
        stats = {
            "target_passes": n_target_passes,
            "proposed": n_proposed,
            "accepted": n_accepted,
            "accept_rate": n_accepted / max(n_proposed, 1),
        }
        return tokens, stats


class SpeculativeSampler:
    """Stochastic speculative decoding (rejection sampling).

    Same pairing as :class:`SpeculativeGenerator`; the draft *samples* its
    proposals and the target accepts/resamples so the emitted stream is
    distributed exactly as direct target sampling with the same
    temperature/top-k/top-p knobs.
    """

    def __init__(self, target: Generator, draft: Generator, k: int = 4, *,
                 temperature: float = 1.0, top_k=None, top_p=None):
        assert target.cfg.vocab == draft.cfg.vocab, "vocabularies differ"
        assert temperature > 0, "use SpeculativeGenerator for greedy"
        self.target = target
        self.draft = draft
        self.k = int(k)
        self._probs = functools.partial(
            filtered_probs, temperature=temperature, top_k=top_k,
            top_p=top_p)

    def generate(self, t_params, d_params, prompt, n_new: int, key):
        """Sample ``n_new`` tokens.  Returns (tokens [1, n_new], stats)."""
        assert prompt.shape[0] == 1, "speculative v1 is batch-1"
        st = self.target.prefill(t_params, prompt)
        sd = self.draft.prefill(d_params, prompt)

        out: list[int] = []
        n_target_passes = 0
        n_proposed = 0
        n_accepted = 0
        while len(out) < n_new:
            L = int(st.kv_lens[0])
            k = min(self.k, self.target.max_seq - 1 - L,
                    self.draft.max_seq - 1 - int(sd.kv_lens[0]))
            if k <= 0:
                key, sub = jax.random.split(key)
                pi = self._probs(st.last_logits[0])
                tok = jax.random.categorical(
                    sub, jnp.log(pi + 1e-30)).astype(jnp.int32)[None]
                out.append(int(tok[0]))
                if len(out) < n_new:
                    st = self.target.step(t_params, st, tok)
                    n_target_passes += 1
                continue

            # 1. Draft samples k proposals (recording its distributions).
            proposals, rhos = [], []
            for _ in range(k):
                key, sub = jax.random.split(key)
                rho = self._probs(sd.last_logits[0])      # [V]
                tok = jax.random.categorical(
                    sub, jnp.log(rho + 1e-30)).astype(jnp.int32)[None]
                rhos.append(rho)
                sd = self.draft.step(d_params, sd, tok)
                proposals.append(int(tok[0]))
            n_proposed += k

            # 2. Target scores all k in one chunk forward.
            chunk = jnp.asarray([proposals], jnp.int32)
            new_caches, logits_all = self.target._chunk_jit(
                t_params, chunk, st.caches, jnp.int32(L),
                quantized=self.target.attn.quantized)
            n_target_passes += 1

            # 3. Left-to-right accept/resample.
            m = 0
            emitted = None
            while m < k:
                pi = self._probs(st.last_logits[0] if m == 0
                                 else logits_all[0, m - 1])
                key, sub = jax.random.split(key)
                accepted, token = speculative_accept_step(
                    pi, rhos[m], jnp.int32(proposals[m]), sub)
                if not bool(accepted):
                    emitted = int(token)      # residual resample; stop
                    break
                out.append(int(token))
                m += 1
            n_accepted += m
            if emitted is None:
                # All k accepted: bonus sample from the target's own
                # next-position distribution.
                pi = self._probs(logits_all[0, k - 1])
                key, sub = jax.random.split(key)
                emitted = int(jax.random.categorical(
                    sub, jnp.log(pi + 1e-30)))
            out.append(emitted)

            # 4. Roll both models to the accepted length + consume emitted.
            bonus = jnp.asarray([emitted], jnp.int32)
            st = GenerationState(
                caches=new_caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=(st.last_logits if m == 0
                             else logits_all[:, m - 1]))
            st = self.target.step(t_params, st, bonus)
            sd = GenerationState(
                caches=sd.caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=sd.last_logits)
            sd = self.draft.step(d_params, sd, bonus)

        tokens = jnp.asarray([out[:n_new]], jnp.int32)
        stats = {
            "target_passes": n_target_passes,
            "proposed": n_proposed,
            "accepted": n_accepted,
            "accept_rate": n_accepted / max(n_proposed, 1),
        }
        return tokens, stats
