"""Greedy speculative decoding: a draft model proposes, the target verifies.

Serving-side addition beyond the reference (its decode story ends at the
attention kernel).  The classic recipe (Leviathan et al. / Chen et al.,
greedy variant): a small draft model autoregressively proposes ``k``
tokens; the target model scores all ``k`` in ONE chunk forward over its
KV cache (models/generate.py ``_chunk_forward`` — the same machinery as
chunked prefill); the longest prefix whose tokens match the target's
greedy choices is accepted, plus one bonus token from the target's own
logits.  Output is **exactly** the target's greedy decode — the draft
only changes how many expensive target passes are needed.

Cache handling is rollback-by-length: the verify chunk writes all ``k``
rows into the target cache, and rejected rows are simply left beyond
``kv_lens`` (decode attention masks by length; later writes overwrite
them).  Same for the draft's own cache.

v1 scope: batch size 1 (per-row accept counts diverge the chunk prefix),
greedy only.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.generate import GenerationState, Generator


def _greedy(logits) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


class SpeculativeGenerator:
    """Pairs a target and a draft :class:`Generator` (same tokenizer/vocab;
    the draft is typically a much smaller config)."""

    def __init__(self, target: Generator, draft: Generator, k: int = 4):
        assert target.cfg.vocab == draft.cfg.vocab, "vocabularies differ"
        self.target = target
        self.draft = draft
        self.k = int(k)

    def generate(self, t_params, d_params, prompt, n_new: int):
        """Greedy-decode ``n_new`` tokens for ``prompt`` [1, S0].

        Returns (tokens [1, n_new], stats dict with ``target_passes`` and
        ``accept_rate``) — tokens are bit-identical to
        ``target.generate(...)`` greedy output.
        """
        assert prompt.shape[0] == 1, "speculative v1 is batch-1"
        st = self.target.prefill(t_params, prompt)
        sd = self.draft.prefill(d_params, prompt)

        out: list[int] = []
        n_target_passes = 0
        n_proposed = 0
        n_accepted = 0
        while len(out) < n_new:
            L = int(st.kv_lens[0])
            k = min(self.k, self.target.max_seq - 1 - L,
                    self.draft.max_seq - 1 - int(sd.kv_lens[0]))
            if k <= 0:
                # No headroom to speculate (last cache slots): fall back
                # to plain greedy target steps — same behavior as
                # Generator.generate, which this must never under-serve.
                tok = _greedy(st.last_logits)
                out.append(int(tok[0]))
                if len(out) < n_new:
                    st = self.target.step(t_params, st, tok)
                    n_target_passes += 1
                continue

            # 1. Draft proposes k greedy tokens (consuming them).
            proposals = []
            for _ in range(k):
                tok = _greedy(sd.last_logits)
                sd = self.draft.step(d_params, sd, tok)
                proposals.append(int(tok[0]))
            n_proposed += k

            # 2. Target scores all k in one chunk forward.
            chunk = jnp.asarray([proposals], jnp.int32)
            new_caches, logits_all = self.target._chunk_jit(
                t_params, chunk, st.caches, jnp.int32(L),
                quantized=self.target.attn.quantized)
            n_target_passes += 1

            # 3. Accept the matching prefix; bonus token from the target.
            expected = int(_greedy(st.last_logits)[0])
            m = 0
            while m < k and proposals[m] == expected:
                out.append(proposals[m])
                m += 1
                expected = int(_greedy(logits_all[:, m - 1])[0])
            n_accepted += m
            bonus = expected  # the correct greedy token at position L+m
            out.append(bonus)

            # 4. Roll both models to the accepted length + consume bonus.
            st = GenerationState(
                caches=new_caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=(st.last_logits if m == 0
                             else logits_all[:, m - 1]))
            st = self.target.step(t_params, st,
                                  jnp.asarray([bonus], jnp.int32))
            sd = GenerationState(
                caches=sd.caches,
                kv_lens=jnp.full((1,), L + m, jnp.int32),
                last_logits=sd.last_logits)  # stale; refreshed by step
            sd = self.draft.step(d_params, sd,
                                 jnp.asarray([bonus], jnp.int32))

        tokens = jnp.asarray([out[:n_new]], jnp.int32)
        stats = {
            "target_passes": n_target_passes,
            "proposed": n_proposed,
            "accepted": n_accepted,
            "accept_rate": n_accepted / max(n_proposed, 1),
        }
        return tokens, stats
