"""W8A8 quantized serving forward for the dense Llama family.

Weight-only prep (`quantize_params_w8a8`, host-side, once per checkpoint)
plus a serving forward (`make_w8a8_forward`) where every projection runs
through the W8A8 TP linears (layers/tp_linear.py):

- column-parallel (fused QKV, gate, up): activations quantize per row
  before the sequence gather, so the overlapped AG-GEMM ring moves int8 —
  half the wire bytes AND the MXU double-rate path;
- row-parallel (attn-out, down): exact local int8 GEMM, dequantized f32
  reduce-scatter (cross-rank sums need dequantized partials).

Norms, RoPE, attention, embed and lm_head stay in the float dtype — the
standard W8A8 recipe quantizes the GEMMs, not the pointwise math.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.quant import quantize_channelwise, w8a8_linear
from triton_dist_tpu.layers.tp_linear import (
    column_parallel_linear_w8a8,
    row_parallel_linear_w8a8,
)
from triton_dist_tpu.models.llama import (
    LlamaConfig,
    _attention,
    _rms_norm,
    _rope,
)


def _quant_col(w):
    """Column-parallel weight: one global per-output-channel quant."""
    q, s = quantize_channelwise(jnp.asarray(w))
    return q, s


def _quant_row(w, world):
    """Row-parallel weight: quantize each rank's k-chunk independently
    (each chunk gets its own [N] channel scales, stacked [world, N])."""
    w = jnp.asarray(w)
    k = w.shape[0]
    assert k % world == 0, (k, world)
    k_loc = k // world
    qs = [quantize_channelwise(w[i * k_loc:(i + 1) * k_loc])
          for i in range(world)]
    return (jnp.concatenate([q for q, _ in qs], axis=0),
            jnp.stack([s for _, s in qs], axis=0))


def _fuse_qkv_by_rank(wq, wk, wv, world):
    """Fuse Q/K/V so a P(None, axis) column shard gives each rank its own
    [wq_chunk | wk_chunk | wv_chunk] block (the per-shard concatenation the
    float path does inside shard_map, done once on the host).  A naive
    global concat would hand rank 0 nothing but Q columns."""
    hq = wq.shape[1] // world
    hk = wk.shape[1] // world
    cols = []
    for r in range(world):
        cols += [wq[:, r * hq:(r + 1) * hq],
                 wk[:, r * hk:(r + 1) * hk],
                 wv[:, r * hk:(r + 1) * hk]]
    return jnp.concatenate(cols, axis=1)


def quantize_params_w8a8(params, cfg: LlamaConfig, world: int) -> dict:
    """Float param tree → W8A8 serving tree (host-side, once).

    Layer keys: ``wqkv_q/wqkv_s`` (fused column weight in per-rank block
    order), ``wgate_q/s``, ``wup_q/s``, ``wo_q/s``, ``wdown_q/s`` (row
    weights with [world, N] stacked scales), float norms; top level keeps
    embed/lm_head/final_norm.
    """
    out = {"embed": params["embed"], "lm_head": params["lm_head"],
           "final_norm": params["final_norm"], "layers": []}
    for layer in params["layers"]:
        wqkv = _fuse_qkv_by_rank(layer["wq"], layer["wk"], layer["wv"],
                                 world)
        qkv_q, qkv_s = _quant_col(wqkv)
        gate_q, gate_s = _quant_col(layer["wgate"])
        up_q, up_s = _quant_col(layer["wup"])
        wo_q, wo_s = _quant_row(layer["wo"], world)
        down_q, down_s = _quant_row(layer["wdown"], world)
        out["layers"].append({
            "attn_norm": layer["attn_norm"], "mlp_norm": layer["mlp_norm"],
            "wqkv_q": qkv_q, "wqkv_s": qkv_s,
            "wgate_q": gate_q, "wgate_s": gate_s,
            "wup_q": up_q, "wup_s": up_s,
            "wo_q": wo_q, "wo_s": wo_s,
            "wdown_q": down_q, "wdown_s": down_s,
        })
    return out


def w8a8_param_specs(cfg: LlamaConfig, axis: str = "tp") -> dict:
    layer = {
        "attn_norm": P(), "mlp_norm": P(),
        "wqkv_q": P(None, axis), "wqkv_s": P(axis),
        "wgate_q": P(None, axis), "wgate_s": P(axis),
        "wup_q": P(None, axis), "wup_s": P(axis),
        "wo_q": P(axis, None), "wo_s": P(axis, None),
        "wdown_q": P(axis, None), "wdown_s": P(axis, None),
    }
    return {"embed": P(), "lm_head": P(), "final_norm": P(),
            "layers": [dict(layer) for _ in range(cfg.n_layers)]}


def place_w8a8_params(qparams, cfg: LlamaConfig, mesh: Mesh,
                      axis: str = "tp") -> dict:
    specs = w8a8_param_specs(cfg, axis)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        qparams, specs, is_leaf=lambda x: isinstance(x, P))


# ---------------------------------------------------------------------------
# W8A8 SERVING (ServeEngine's weight plane — docs/serving.md "Quantized
# serving"): QKV stays float (RoPE/attention/paged-KV addressing are
# untouched, and the serving forwards contract wq/wk/wv per head), while
# the two hook seams every serving forward already exposes — ``out_proj``
# and ``ffn`` — run the W8A8 GEMMs.  World-1 uses the hooks bare; mesh
# heads-TP passes ``axis=`` so the row-parallel halves psum their
# dequantized partials (cross-rank sums need dequantized f32 — the
# layers/tp_linear.py recipe).
# ---------------------------------------------------------------------------


def quantize_serve_params(params, cfg: LlamaConfig, world: int = 1) -> dict:
    """Float serving tree → the W8A8 serving tree (host-side, once).

    Unlike :func:`quantize_params_w8a8` (the full-forward tree with fused
    QKV), serving keeps ``wq``/``wk``/``wv`` float — the paged forwards
    reshape QKV per head and feed RoPE + the paged-attention kernels,
    which stay in the float dtype per the standard W8A8 recipe.  Only the
    hook-seam weights quantize: ``wgate``/``wup`` per output channel
    (column-parallel under heads-TP), ``wo``/``wdown`` per rank k-chunk
    with ``[world, N]`` stacked scales (row-parallel — each rank
    dequantizes its own chunk exactly before the psum)."""
    out = {"embed": params["embed"], "lm_head": params["lm_head"],
           "final_norm": params["final_norm"], "layers": []}
    for layer in params["layers"]:
        gate_q, gate_s = _quant_col(layer["wgate"])
        up_q, up_s = _quant_col(layer["wup"])
        wo_q, wo_s = _quant_row(layer["wo"], world)
        down_q, down_s = _quant_row(layer["wdown"], world)
        out["layers"].append({
            "attn_norm": layer["attn_norm"], "mlp_norm": layer["mlp_norm"],
            "wq": layer["wq"], "wk": layer["wk"], "wv": layer["wv"],
            "wgate_q": gate_q, "wgate_s": gate_s,
            "wup_q": up_q, "wup_s": up_s,
            "wo_q": wo_q, "wo_s": wo_s,
            "wdown_q": down_q, "wdown_s": down_s,
        })
    return out


def w8a8_serve_param_specs(cfg: LlamaConfig, axis: str = "tp") -> dict:
    """PartitionSpec tree matching :func:`quantize_serve_params` under
    heads-TP: float QKV shard column-parallel exactly as
    ``llama.param_specs`` says; quantized column weights shard their
    output channels (scales ride along on the same axis) and quantized
    row weights shard their k-chunks (each rank holds its own [1, N]
    scale row)."""
    layer = {
        "attn_norm": P(), "mlp_norm": P(),
        "wq": P(None, axis), "wk": P(None, axis), "wv": P(None, axis),
        "wgate_q": P(None, axis), "wgate_s": P(axis),
        "wup_q": P(None, axis), "wup_s": P(axis),
        "wo_q": P(axis, None), "wo_s": P(axis, None),
        "wdown_q": P(axis, None), "wdown_s": P(axis, None),
    }
    return {"embed": P(), "lm_head": P(), "final_norm": P(),
            "layers": [dict(layer) for _ in range(cfg.n_layers)]}


def w8a8_serve_out_proj(o2, layer, *, axis=None, impl="auto",
                        interpret=False):
    """``out_proj`` hook: attention output through the W8A8 GEMM.
    ``layer["wo_s"][0]`` is THIS rank's scale row — world-1 stacks one,
    and under heads-TP the ``P(axis, None)`` shard hands each rank
    exactly its own."""
    y = w8a8_linear(o2, layer["wo_q"], layer["wo_s"][0], impl=impl,
                    interpret=interpret)
    return jax.lax.psum(y, axis) if axis is not None else y


def w8a8_serve_ffn(h2, layer, *, axis=None, impl="auto", interpret=False):
    """``ffn`` hook: the SwiGLU MLP with all three GEMMs W8A8 (same
    activation math as ``generate._dense_prompt_ffn``)."""
    gate = w8a8_linear(h2, layer["wgate_q"], layer["wgate_s"], impl=impl,
                       interpret=interpret)
    up = w8a8_linear(h2, layer["wup_q"], layer["wup_s"], impl=impl,
                     interpret=interpret)
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(h2.dtype) * up
    y = w8a8_linear(act, layer["wdown_q"], layer["wdown_s"][0], impl=impl,
                    interpret=interpret)
    return jax.lax.psum(y, axis) if axis is not None else y


def w8a8_forward_shard(qparams, tokens_shard, cfg: LlamaConfig, *,
                       axis="tp", impl="auto", interpret=False):
    """Per-device quantized forward (the W8A8 twin of
    ``llama.forward_shard``).  tokens_shard [S_loc, B] → logits f32."""
    world = jax.lax.axis_size(axis)
    hd = cfg.head_dim
    hq_loc = cfg.n_heads // world
    hkv_loc = cfg.n_kv_heads // world
    lin_c = functools.partial(column_parallel_linear_w8a8, axis=axis,
                              impl=impl, interpret=interpret)
    lin_r = functools.partial(row_parallel_linear_w8a8, axis=axis,
                              impl=impl, interpret=interpret)

    x = qparams["embed"][tokens_shard]  # [S_loc, B, D]
    s_loc, b, _ = x.shape
    full_positions = jnp.arange(world * s_loc, dtype=jnp.int32)

    for layer in qparams["layers"]:
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        qkv = lin_c(h.reshape(s_loc * b, cfg.dim), layer["wqkv_q"],
                    layer["wqkv_s"])
        qkv = qkv.reshape(world * s_loc, b, (hq_loc + 2 * hkv_loc) * hd)
        q, k, v = jnp.split(
            qkv, [hq_loc * hd, (hq_loc + hkv_loc) * hd], axis=-1)
        q = _rope(q.reshape(-1, b, hq_loc, hd), full_positions,
                  cfg.rope_theta)
        k = _rope(k.reshape(-1, b, hkv_loc, hd), full_positions,
                  cfg.rope_theta)
        v = v.reshape(-1, b, hkv_loc, hd)
        o = _attention(q, k, v, cfg)
        o = o.reshape(world * s_loc * b, hq_loc * hd)
        x = x + lin_r(o, layer["wo_q"], layer["wo_s"][0]).reshape(
            s_loc, b, cfg.dim)

        h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        h2 = h.reshape(s_loc * b, cfg.dim)
        gate = lin_c(h2, layer["wgate_q"], layer["wgate_s"])
        up = lin_c(h2, layer["wup_q"], layer["wup_s"])
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        x = x + lin_r(act, layer["wdown_q"],
                      layer["wdown_s"][0]).reshape(s_loc, b, cfg.dim)

    x = _rms_norm(x, qparams["final_norm"], cfg.norm_eps)
    return jnp.dot(x, qparams["lm_head"],
                   preferred_element_type=jnp.float32)


def make_w8a8_forward(cfg: LlamaConfig, mesh: Mesh, *, axis="tp",
                      impl="auto", interpret=False):
    """Jitted quantized forward over the mesh: (qparams, tokens [S, B]
    P(axis)) → logits [S, B, vocab] P(axis)."""
    specs = w8a8_param_specs(cfg, axis)
    fn = jax.shard_map(
        functools.partial(w8a8_forward_shard, cfg=cfg, axis=axis,
                          impl=impl, interpret=interpret),
        mesh=mesh,
        in_specs=(specs, P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(fn)
