"""Pipelined (pp × dp × tp/sp/ep) training steps for the model families.

Combines the SPMD GPipe schedule (parallel/pipeline.py) with the kernel-
wired transformer blocks: layers stack along a leading axis sharded over
the ``pp`` mesh axis; inside each stage the blocks run the overlapped TP
kernels (sequence-parallel activations) and — for the MoE family — the EP
AllToAll expert path.  One ``shard_map`` program therefore exercises every
parallelism the framework offers:

  dp  — batch axis, gradient psum
  pp  — layer pipeline, ppermute carries
  tp  — tensor-parallel projections (AG-GEMM / GEMM-RS)
  sp  — sequence-sharded activations between blocks (Megatron SP layout)
  ep  — MoE expert sharding + token AllToAll (MoE family)

The reference implements none of this composition (it is a kernel library;
SURVEY.md §2.5): this module is where the TPU build shows the kernels are
actually composable under jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import llama as L
from triton_dist_tpu.models import moe as MoE
from triton_dist_tpu.parallel.pipeline import pipeline_spmd, stack_layer_params


def _is_moe(cfg) -> bool:
    return isinstance(cfg, MoE.MoEConfig)


def init_pp_params(cfg, key) -> dict:
    """Same leaves as the family's init_params, with layers stacked [L, ...]."""
    base = (MoE.init_params(cfg, key) if _is_moe(cfg)
            else L.init_params(cfg, key))
    base["layers"] = stack_layer_params(base["layers"])
    return base


def pp_param_specs(cfg, *, tp_axis="tp", pp_axis="pp") -> dict:
    """Family specs with the stacked layer axis sharded over ``pp``."""
    base = (MoE.param_specs(cfg, tp_axis) if _is_moe(cfg)
            else L.param_specs(cfg))
    layer0 = base["layers"][0]
    if not _is_moe(cfg) and tp_axis != "tp":
        raise NotImplementedError("llama specs are tp-named")
    stacked = {k: P(pp_axis, *spec) for k, spec in layer0.items()}
    base["layers"] = stacked
    return base


def _block(layer, carry, cfg, *, tp_axis, impl, interpret):
    """One decoder layer on one microbatch carry (x, aux)."""
    x, aux = carry
    lcfg = cfg.as_llama() if _is_moe(cfg) else cfg
    x = L.attention_block_shard(x, layer, lcfg, axis=tp_axis, impl=impl,
                                interpret=interpret)
    if _is_moe(cfg):
        x, d_aux = MoE.moe_block_shard(x, layer, cfg, axis=tp_axis,
                                       impl=impl, interpret=interpret)
        aux = aux + d_aux
    else:
        x = L.mlp_block_shard(x, layer, cfg, axis=tp_axis, impl=impl,
                              interpret=interpret)
    return x, aux


def make_pp_train_step(cfg, mesh: Mesh, *, tp_axis="tp", pp_axis="pp",
                       dp_axis=None, n_micro=4, impl="auto",
                       interpret=False, lr=1e-3, remat=False,
                       hier_dp_fast_axis=None):
    """SGD step over a (dp ×) pp × tp mesh with GPipe microbatching.

    Input tokens/targets: [S, B] (sequence sharded over tp, batch over dp);
    B is split into ``n_micro`` microbatches host-side.  Returns
    (jitted step, specs).  Gradient sync rule: every leaf is psum'd over
    each mesh axis its spec does NOT mention (pipeline masking zeroes the
    contributions of stages that don't own a replicated leaf's compute).

    ``hier_dp_fast_axis`` (r5, dp-over-DCN training): when the dp axis
    rides the slow DCN tier, set this to an ICI axis — the dp gradient
    reduction of every leaf REPLICATED over that axis is bucketed through
    ``kernels/hierarchical.hier_grad_allreduce`` (RS over ICI → psum over
    DCN on the 1/T band → AG over ICI: each chip ships 1/T of the
    gradient bytes across DCN).  Leaves sharded over the fast axis keep
    the direct dp psum (they are already 1/T-sized).
    """
    specs = pp_param_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis)
    mesh_axes = tuple(a for a in (tp_axis, pp_axis, dp_axis) if a)
    tok_spec = P(None, tp_axis, dp_axis) if dp_axis else P(None, tp_axis)
    coef = getattr(cfg, "aux_loss_coef", 0.0)

    def loss_shard(params, tokens_m, targets_m):
        """tokens_m: [n_micro, S_loc, mb] int32.  Per-device contribution:
        psum over ALL mesh axes == global loss."""
        n_stages = jax.lax.axis_size(pp_axis)
        is_last = jax.lax.axis_index(pp_axis) == n_stages - 1

        x = params["embed"][tokens_m]             # [n_micro, S_loc, mb, D]
        xs = (x, jnp.zeros((n_micro,), jnp.float32))
        block = functools.partial(_block, cfg=cfg, tp_axis=tp_axis,
                                  impl=impl, interpret=interpret)
        if remat:
            # Recompute each layer in the backward pipeline instead of
            # stashing n_micro x n_layers activation sets.  prevent_cse is
            # unnecessary under lax.scan (the schedule's scans already
            # block the problematic CSE) and would pepper the hot loop
            # with optimization barriers.
            block = jax.checkpoint(block, prevent_cse=False)
        outs_x, outs_aux = pipeline_spmd(
            block, params["layers"], xs, axis=pp_axis, n_micro=n_micro)

        # Head + CE on the last stage only (garbage elsewhere — mask it).
        h = L._rms_norm(outs_x, params["final_norm"], cfg.norm_eps)
        logits = jnp.dot(h, params["lm_head"],
                         preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(
            logp, targets_m[..., None].astype(jnp.int32), axis=-1)[..., 0]
        denom = ll.size * jax.lax.axis_size(tp_axis)
        aux = jnp.sum(outs_aux) / n_micro
        if dp_axis is not None:
            denom = denom * jax.lax.axis_size(dp_axis)
            aux = aux / jax.lax.axis_size(dp_axis)
        local = -jnp.sum(ll) / denom + coef * aux
        return jnp.where(is_last, local, 0.0)

    def step_shard(params, tokens_m, targets_m):
        local_loss, grads = jax.value_and_grad(loss_shard)(
            params, tokens_m, targets_m)
        loss = jax.lax.psum(local_loss, mesh_axes)

        if hier_dp_fast_axis is None:
            def _reduce(g, spec):
                axes = tuple(a for a in mesh_axes if a not in spec)
                return jax.lax.psum(g, axes) if axes else g

            grads = jax.tree.map(_reduce, grads, specs,
                                 is_leaf=lambda x: isinstance(x, P))
        else:
            from triton_dist_tpu.kernels.hierarchical import (
                hier_grad_allreduce)

            assert dp_axis is not None, "hier_dp_fast_axis needs dp_axis"
            fast = hier_dp_fast_axis

            def _mentions(spec, axis):
                for e in spec:
                    if isinstance(e, (tuple, list)):
                        if axis in e:
                            return True
                    elif e == axis:
                        return True
                return False

            leaves, treedef = jax.tree.flatten(grads)
            spec_leaves = jax.tree.flatten(
                specs, is_leaf=lambda x: isinstance(x, P))[0]
            # Bucketed leaves (fast-replicated): their fast-axis masking
            # psum FUSES into the two-tier reduction — the hier pass IS
            # sum over (fast, dp), so _pre must not pre-sum fast (doing
            # both double-counts by a factor of T).  Fast-sharded leaves
            # (already 1/T bytes) psum straight across dp.
            bucket_set = {i for i, s in enumerate(spec_leaves)
                          if not _mentions(s, fast)}

            def _pre(i, g, spec):
                skip = ({dp_axis, fast} if i in bucket_set else {dp_axis})
                axes = tuple(a for a in mesh_axes
                             if not _mentions(spec, a) and a not in skip)
                return jax.lax.psum(g, axes) if axes else g

            leaves = [_pre(i, g, s) for i, (g, s)
                      in enumerate(zip(leaves, spec_leaves))]
            bucket_ix = sorted(bucket_set)
            if bucket_ix:
                bucket = hier_grad_allreduce(
                    [leaves[i] for i in bucket_ix], slow_axis=dp_axis,
                    fast_axis=fast, interpret=interpret)
                for i, g in zip(bucket_ix, bucket):
                    leaves[i] = g
            leaves = [g if i in bucket_set else jax.lax.psum(g, dp_axis)
                      for i, g in enumerate(leaves)]
            grads = jax.tree.unflatten(treedef, leaves)
        new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                                  params, grads)
        return new_params, loss

    inner = jax.shard_map(
        step_shard,
        mesh=mesh,
        in_specs=(specs, tok_spec, tok_spec),
        out_specs=(specs, P()),
        check_vma=False,
    )

    def step(params, tokens, targets):
        """tokens/targets: [S, B]; B → n_micro × mb microbatches."""
        S, B = tokens.shape
        assert B % n_micro == 0, (B, n_micro)
        reshape = lambda t: jnp.moveaxis(
            t.reshape(S, n_micro, B // n_micro), 1, 0)
        return inner(params, reshape(tokens), reshape(targets))

    return jax.jit(step), specs


def place_pp_params(params, cfg, mesh, *, tp_axis="tp", pp_axis="pp"):
    specs = pp_param_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis)
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)
