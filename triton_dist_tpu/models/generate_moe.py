"""Autoregressive generation for the MoE family (EP decode serving).

Extends :class:`models.generate.Generator` to Mixtral/DeepSeek-class MoE
models: attention decodes over the sequence-parallel KV cache exactly as the
dense family does (layers/sp_flash_decode.py), while the FFN runs
**expert-parallel** — expert stacks stay sharded over the mesh axis and each
device computes only its own experts' contribution for the decode batch,
followed by one psum.  This is the standard small-batch EP decode layout:
at B tokens/step the AllToAll's token shuffle has nothing to amortize, so
replicate-activations + shard-experts + psum is both simpler and faster
(the large-batch dispatch path remains `layers/moe_inference.py`).

The reference has no MoE generation story at all (its EP machinery stops at
the kernel tests); this module is where the framework's serving stack and
MoE stack meet.

Serving placement (`place_params_serving`): expert stacks P(axis, None,
None), everything else replicated — the decode analog of the training
layout in ``models/moe.param_specs``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.moe_utils import topk_routing
from triton_dist_tpu.models.generate import Generator
from triton_dist_tpu.models.moe import MoEConfig
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


def place_params_serving(params, cfg: MoEConfig, mesh: Mesh,
                         axis: str = "sp") -> dict:
    """EP-shard the expert stacks; replicate everything else (the decode
    layout: the sharded things are the KV cache and the experts)."""

    def spec_of(path_key):
        return (P(axis, None, None)
                if path_key in ("w_gate", "w_up", "w_down") else P())

    def place(tree):
        out = {}
        for k, v in tree.items():
            if k == "layers":
                out[k] = [place(layer) for layer in v]
            else:
                out[k] = jax.device_put(
                    v, NamedSharding(mesh, spec_of(k)))
        return out

    return place(params)


def moe_ffn_decode_shard(h, router, w_gate, w_up, w_down, *, axis,
                         n_experts, topk):
    """One decode step's expert FFN, per device (inside shard_map).

    h [B, D] replicated; router [D, E] replicated; w_* are this device's
    expert slabs [epr, D, F] / [epr, F, D].  Each device accumulates the
    weighted SwiGLU of its own experts for every token, then a psum sums
    the topk contributions across owners.  Returns [B, D] replicated.
    """
    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    epr = n_experts // world

    logits = jnp.dot(h.astype(jnp.float32), router)
    weights, experts = topk_routing(logits, topk)  # [B, topk]

    y = jnp.zeros_like(h, shape=h.shape, dtype=jnp.float32)
    for e_loc in range(epr):
        e_glob = me * epr + e_loc
        w_tok = jnp.sum(
            jnp.where(experts == e_glob, weights, 0.0), axis=-1)  # [B]
        g = jnp.dot(h, w_gate[e_loc], preferred_element_type=jnp.float32)
        u = jnp.dot(h, w_up[e_loc], preferred_element_type=jnp.float32)
        act = (jax.nn.silu(g) * u).astype(h.dtype)
        y += w_tok[:, None] * jnp.dot(act, w_down[e_loc],
                                      preferred_element_type=jnp.float32)
    return jax.lax.psum(y, axis).astype(h.dtype)


def _moe_prompt_ffn(h2, layer, cfg: MoEConfig):
    """Prompt-phase routed FFN as a dense one-hot sum over ALL experts.

    Correctness-first: E sequential expert passes over the whole prompt
    (XLA gathers each EP-sharded slab).  Prefill happens once per request;
    the dispatch-based path (models/moe.moe_ffn_shard) is the throughput
    alternative when prompts are long enough to shard.
    """
    logits = jnp.dot(h2.astype(jnp.float32), layer["router"])
    weights, experts = topk_routing(logits, cfg.topk)
    y = jnp.zeros(h2.shape, jnp.float32)
    for e in range(cfg.n_experts):
        w_tok = jnp.sum(jnp.where(experts == e, weights, 0.0), axis=-1)
        g = jnp.dot(h2, layer["w_gate"][e],
                    preferred_element_type=jnp.float32)
        u = jnp.dot(h2, layer["w_up"][e],
                    preferred_element_type=jnp.float32)
        act = (jax.nn.silu(g) * u).astype(h2.dtype)
        y += w_tok[:, None] * jnp.dot(act, layer["w_down"][e],
                                      preferred_element_type=jnp.float32)
    return y.astype(h2.dtype)


def _moe_prompt_forward(params, tokens, *, cfg: MoEConfig,
                        impl: str = "auto", interpret: bool = False):
    """Full-prompt forward returning per-layer (K, V) caches + logits —
    generate._prompt_forward's attention/cache body with the MoE FFN
    swapped in via its ``ffn`` hook."""
    from triton_dist_tpu.models.generate import _prompt_forward

    return _prompt_forward(
        params, tokens, cfg=cfg,
        ffn=functools.partial(_moe_prompt_ffn, cfg=cfg),
        impl=impl, interpret=interpret)


class MoEGenerator(Generator):
    """Greedy/stochastic decoder for the MoE family.

    Same API as :class:`Generator` (prefill / step / generate, sampling via
    ``key=``); params come from ``models.moe.init_params`` placed with
    :func:`place_params_serving` on the same mesh axis the KV cache shards
    over.
    """

    def __init__(self, cfg: MoEConfig, mesh: Mesh, *, axis: str = "sp",
                 max_seq: int | None = None, impl: str = "auto",
                 interpret: bool = False, kv_dtype=None):
        super().__init__(cfg, mesh, axis=axis, max_seq=max_seq, impl=impl,
                         interpret=interpret, kv_dtype=kv_dtype)
        self._prefill_jit = jax.jit(functools.partial(
            _moe_prompt_forward, cfg=cfg, impl=impl, interpret=interpret))
        from triton_dist_tpu.models.generate import (
            _chunk_forward,
            _verify_forward,
        )
        self._chunk_jit = jax.jit(
            functools.partial(_chunk_forward, cfg=cfg,
                              ffn=functools.partial(_moe_prompt_ffn,
                                                    cfg=cfg),
                              impl=impl, interpret=interpret,
                              mesh=mesh, axis=axis),
            static_argnames=("quantized", "extent"),
            donate_argnums=(2,))
        self._verify_jit = jax.jit(
            functools.partial(_verify_forward, cfg=cfg,
                              ffn=functools.partial(_moe_prompt_ffn,
                                                    cfg=cfg),
                              impl=impl, interpret=interpret),
            donate_argnums=(2,))

    def _ffn_decode(self, h, layer):
        """Decode-step FFN hook (generate._token_forward): EP
        masked-expert compute + psum.  The attention/cache body of
        ``_step_impl`` is inherited — one copy of the math."""
        cfg: MoEConfig = self.cfg
        fn = cached_shard_jit(
            moe_ffn_decode_shard,
            self.mesh,
            (P(), P(), P(self.axis, None, None), P(self.axis, None, None),
             P(self.axis, None, None)),
            P(),
            axis=self.axis, n_experts=cfg.n_experts, topk=cfg.topk,
        )
        return fn(h, layer["router"], layer["w_gate"], layer["w_up"],
                  layer["w_down"])
