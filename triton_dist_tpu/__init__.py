"""triton_dist_tpu — a TPU-native distributed compute/communication-overlap framework.

This package provides the capabilities of ByteDance's Triton-distributed
(reference: github.com/ByteDance-Seed/Triton-distributed) re-designed from
scratch for TPU hardware:

- **Runtime** (`triton_dist_tpu.runtime`): bootstrap, device mesh management,
  symmetric-memory abstraction, topology introspection, benchmarking and
  profiling utilities.  (Reference analog: ``python/triton_dist/utils.py`` +
  ``pynvshmem``.)
- **Language** (`triton_dist_tpu.language`): the distributed primitive toolkit
  usable inside Pallas kernels — ``wait`` / ``notify`` / ``symm_at`` /
  ``putmem_*`` / barriers — built on Mosaic device semaphores and async remote
  DMA over ICI.  (Reference analog: the MLIR ``distributed`` dialect +
  ``triton_dist.language`` + ``libshmem_device``.)
- **Kernels** (`triton_dist_tpu.kernels`): the distributed kernel library —
  allgather (ring/pull/push/low-latency), reduce-scatter, overlapped
  AllGather-GEMM and GEMM-ReduceScatter, MoE dispatch/combine all-to-all,
  distributed flash-decode.  (Reference analog:
  ``python/triton_dist/kernels/nvidia``.)
- **Layers** (`triton_dist_tpu.layers`): model-facing modules
  (sequence-parallel decode attention, EP all-to-all layer, allgather layer,
  TP linear layers).  (Reference analog: ``python/triton_dist/layers``.)
- **Models** (`triton_dist_tpu.models`): end-to-end model families (Llama-style
  dense transformer, Mixtral/DeepSeek-style MoE) wired through the kernels.
- **Tools** (`triton_dist_tpu.tools`): contextual autotuner, AOT export,
  analytic performance models.

Design stance (TPU-first, not a port):

* SPMD over ``jax.sharding.Mesh`` + ``shard_map`` replaces
  torchrun/NCCL/NVSHMEM process groups.  Rank = ``jax.lax.axis_index``.
* The NVSHMEM symmetric heap maps to SPMD symmetry: under ``shard_map`` every
  device holds an identically-shaped shard, so "symmetric buffers" are just
  sharded arrays; remote addressing is Mosaic remote DMA by logical device id.
* CUDA streams map to Mosaic async DMA queued against MXU compute *inside one
  fused Pallas kernel* (TPU exposes no user streams; overlap lives in-kernel).
* Every collective op has two interchangeable backends: ``"xla"`` (lax
  collectives — XLA's latency-hiding scheduler is the baseline to beat) and
  ``"pallas"`` (hand-scheduled kernels with remote DMA + semaphores).
"""

__version__ = "0.1.0"

# Version shims first: everything below (and every later submodule import)
# assumes the jax>=0.6 names (jax.shard_map, pltpu.CompilerParams).
from triton_dist_tpu.runtime import compat as _compat

_compat.apply()

from triton_dist_tpu.runtime import (  # noqa: F401
    initialize_distributed,
    get_mesh,
    assert_allclose,
    dist_print,
    perf_func,
)
