"""Model-facing layers wrapping the distributed kernel library.

Reference analog: ``python/triton_dist/layers/nvidia/`` —
``SpGQAFlashDecodeAttention``, ``EPAll2AllLayer``, ``AllGatherLayer``.

TPU-native additions: differentiable sequence-parallel TP linears
(``column_parallel_linear`` / ``row_parallel_linear``) whose custom VJPs
keep the backward pass overlapped too (the reference is inference-only
kernels; training-capable layers are where the TPU build goes further).
"""

from triton_dist_tpu.layers.tp_linear import (  # noqa: F401
    column_parallel_linear,
    column_parallel_linear_w8a8,
    row_parallel_linear,
    row_parallel_linear_w8a8,
)
from triton_dist_tpu.layers.sp_flash_decode import (  # noqa: F401
    SpGQAFlashDecodeAttention,
)
from triton_dist_tpu.layers.ep_a2a import EPAll2AllLayer  # noqa: F401
from triton_dist_tpu.layers.allgather_layer import AllGatherLayer  # noqa: F401
from triton_dist_tpu.layers.moe_inference import (  # noqa: F401
    DistributedMoELayer,
)
