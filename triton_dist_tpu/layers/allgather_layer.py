"""AllGather layer — stage-buffered wrapper over the fast-allgather variants.

Reference analog: ``python/triton_dist/layers/nvidia/low_latency_allgather_layer.py``
(``AllGatherLayer``, :31-195) — a thin module over all ``fast_allgather``
variants that owns the staged symm buffer and a ``signal_target`` generation
counter, growing/shrinking the buffer as payload sizes change.

TPU-native design: buffers and signals are kernel-local (fresh semaphores
per invocation — Mosaic guarantees), so the generation-counter machinery has
nothing to manage; what remains is the *policy* surface: pick the gather
strategy per payload size and mesh shape, and pack/unpack multi-tensor
payloads into one gather (the reference's out ⊕ lse packing).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp

from triton_dist_tpu.kernels.low_latency_allgather import (
    FastAllGatherContext,
    fast_allgather,
    pack_payload,
    unpack_payload,
)
from triton_dist_tpu.kernels.allgather import (
    AllGatherMethod,
    all_gather,
    create_allgather_context,
)

# Payloads at or below this many bytes per device take the one-shot
# full-mesh push (latency-bound); larger ones take the ring (bandwidth-
# bound).  Reference: the dispatcher's speed tables (low_latency_allgather
# .py:971+ picks pull/push-2d/push-3d by size and topology).
LATENCY_BOUND_BYTES = 1 << 20


@dataclass
class AllGatherLayer:
    """Reference analog: ``AllGatherLayer`` (low_latency_allgather_layer.py)."""

    ctx: FastAllGatherContext
    latency_bound_bytes: int = LATENCY_BOUND_BYTES

    def forward(self, x):
        """Gather ``x`` (sharded on dim 0 over ctx.axis) by size policy."""
        nbytes = x.size * x.dtype.itemsize // max(self.ctx.world, 1)
        if nbytes <= self.latency_bound_bytes:
            return self.forward_push(x)
        return self.forward_ring(x)

    def forward_push(self, x):
        """One-shot full-mesh push (the reference's LL/push-2d family)."""
        return fast_allgather(x, self.ctx)

    def forward_ring(self, x):
        """Bandwidth-bound ring gather (the reference's 1d-ring family)."""
        method = (AllGatherMethod.XLA if self.ctx.impl == "xla"
                  else AllGatherMethod.RING_1D)
        ring_ctx = create_allgather_context(
            self.ctx.mesh, axis=self.ctx.axis, method=method,
            interpret=self.ctx.interpret)
        return all_gather(x, ring_ctx)

    def forward_packed(self, out, lse):
        """Gather (out ⊕ lse) in one payload; returns per-rank partials.

        Reference: sp_flash_decode's packed partial gather
        (sp_flash_decode_layer.py:135-137).
        """
        buf = pack_payload(out.astype(jnp.float32), lse.astype(jnp.float32))
        world = self.ctx.world
        gathered = fast_allgather(buf, self.ctx)
        return unpack_payload(gathered.reshape((world, -1) + buf.shape[1:]))
