"""Differentiable sequence-parallel tensor-parallel linears.

The Megatron-style sequence-parallel TP pattern is exactly the reference's
flagship kernel pair (SURVEY.md §2.5):

* **column-parallel** (QKV / up-proj): tokens are sequence-sharded; the
  weight is output-column-sharded.  Forward = overlapped AllGather-GEMM
  (``allgather_gemm.py``), output has full sequence, sharded features.
* **row-parallel** (attn-out / down-proj): input features are sharded; the
  weight is input-row-sharded.  Forward = overlapped GEMM-ReduceScatter
  (``gemm_reduce_scatter.py``), output is sequence-sharded again.

The backward passes are each other's duals, so training stays overlapped:

  column fwd:  C = AG(A) @ B
  column bwd:  dA = GEMM-RS(dC @ Bᵀ)      (ring RS overlapped)
               dB = AG(A)ᵀ @ dC           (local MXU, AG(A) saved from fwd)
  row fwd:     C = RS(A @ B)
  row bwd:     dA = AG(dC) @ Bᵀ           (ring AG overlapped)
               dB = Aᵀ @ AG(dC)           (local MXU)

Everything here is **shard-level**: call inside ``shard_map``.  The reference
has no training story at all (kernel library only) — this module is where the
TPU build exceeds it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels.allgather_gemm import ag_gemm_shard
from triton_dist_tpu.kernels.gemm_reduce_scatter import gemm_rs_shard


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def column_parallel_linear(a_shard, b_shard, axis, impl="auto",
                           interpret=False):
    """[m_loc, K] x [K, n_loc] -> [M, n_loc] via overlapped AG-GEMM.

    ``a_shard`` is the sequence-sharded activation, ``b_shard`` the
    column-sharded weight.  Returns the full-sequence activation with local
    feature columns.
    """
    _, c = _col_fwd_impl(a_shard, b_shard, axis, impl, interpret)
    return c


def _col_fwd_impl(a_shard, b_shard, axis, impl, interpret):
    kw = dict(axis=axis, impl=impl,
              interpret=interpret)
    a_full, c = ag_gemm_shard(a_shard, b_shard, **kw)
    return a_full, c


def _col_fwd(a_shard, b_shard, axis, impl, interpret):
    a_full, c = _col_fwd_impl(a_shard, b_shard, axis, impl, interpret)
    return c, (a_full, b_shard)


def _col_bwd(axis, impl, interpret, res, dc):
    a_full, b_shard = res
    # dA = reduce_scatter(dC @ B^T) over the sequence axis — the ring
    # GEMM-RS kernel with K playing the sharded-feature role.
    da = gemm_rs_shard(dc, b_shard.T, axis=axis, impl=impl,
                       interpret=interpret)
    # dB = AG(A)^T @ dC — local MXU matmul on the saved gathered input.
    db = jnp.dot(a_full.T, dc, preferred_element_type=jnp.float32).astype(
        b_shard.dtype)
    return da, db


column_parallel_linear.defvjp(_col_fwd, _col_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def row_parallel_linear(a_shard, b_shard, axis, impl="auto",
                        interpret=False):
    """[M, k_loc] x [k_loc, N] -> [m_loc, N] via overlapped GEMM-RS.

    ``a_shard`` has full sequence with local feature columns, ``b_shard``
    the row-sharded weight.  Returns the sequence-sharded output, fully
    summed over feature shards.
    """
    return gemm_rs_shard(a_shard, b_shard, axis=axis, impl=impl,
                         interpret=interpret)


def _row_fwd(a_shard, b_shard, axis, impl, interpret):
    c = row_parallel_linear(a_shard, b_shard, axis, impl, interpret)
    return c, (a_shard, b_shard)


def _row_bwd(axis, impl, interpret, res, dc):
    a_shard, b_shard = res
    # dA = AG(dC) @ B^T — the ring AG-GEMM kernel; its gathered output is
    # reused for dB, so the gather happens once.
    dc_full, da = ag_gemm_shard(dc, b_shard.T, axis=axis, impl=impl,
                                interpret=interpret)
    db = jnp.dot(a_shard.T, dc_full, preferred_element_type=jnp.float32
                 ).astype(b_shard.dtype)
    return da, db


row_parallel_linear.defvjp(_row_fwd, _row_bwd)


# ---------------------------------------------------------------------------
# W8A8 serving variants (no VJP — inference path; see kernels/quant.py)
# ---------------------------------------------------------------------------


def column_parallel_linear_w8a8(a_shard, w_q, w_scale, axis, impl="auto",
                                interpret=False):
    """W8A8 column-parallel forward: int8 rides the overlapped AG-GEMM.

    a_shard [m_loc, K] float; w_q [K, n_loc] int8 with per-channel
    ``w_scale`` [n_loc].  Activations quantize per local row *before* the
    gather, their scales allgather alongside (a tiny [m_loc] f32 vector),
    and the ring kernel moves int8 — half the wire bytes of the bf16 path
    on top of the double-rate MXU.  Returns [M, n_loc] in a_shard.dtype.
    """
    from triton_dist_tpu.kernels.quant import quantize_rowwise

    a_q, a_scale = quantize_rowwise(a_shard)
    _, acc = ag_gemm_shard(a_q, w_q, axis=axis, impl=impl,
                           interpret=interpret)  # [M, n_loc] i32, exact
    a_scale_full = jax.lax.all_gather(a_scale, axis, axis=0, tiled=True)
    y = acc.astype(jnp.float32) * a_scale_full[:, None] * w_scale[None, :]
    return y.astype(a_shard.dtype)


def row_parallel_linear_w8a8(a_shard, w_q, w_scale, axis, impl="auto",
                             interpret=False):
    """W8A8 row-parallel forward: local int8 GEMM + f32 reduce-scatter.

    a_shard [M, k_loc] float; w_q [k_loc, N] int8 quantized per output
    channel *per rank* (each rank's weight chunk has its own ``w_scale``
    [N]).  Unlike the AG side, the cross-rank reduction must sum
    *dequantized* partials (each rank's int32 partial carries different
    scales), so the exact int8 GEMM runs locally and the psum_scatter
    moves f32.  Returns [m_loc, N] in a_shard.dtype.
    """
    from triton_dist_tpu.kernels.quant import matmul_i8, quantize_rowwise

    a_q, a_scale = quantize_rowwise(a_shard)
    acc = matmul_i8(a_q, w_q, impl=impl, interpret=interpret)  # [M, N] i32
    partial = acc.astype(jnp.float32) * a_scale[:, None] * w_scale[None, :]
    out = jax.lax.psum_scatter(partial, axis, scatter_dimension=0,
                               tiled=True)
    return out.astype(a_shard.dtype)
