"""Expert-parallel AllToAll layer — MoE token dispatch/combine.

Reference analog: ``python/triton_dist/layers/nvidia/ep_a2a_layer.py``
(``EPAll2AllLayer``, :40-240) — ``dispatch()`` allgathers split counts,
precomputes receive offsets (with a pinned-memory CPU readback for the
output allocation, ep_a2a.py:353-387) and putmem's each token to its expert
ranks; ``combine()`` reverses the shuffle and topk-reduces.

TPU-native design (NOT a port):

* **No dynamic shapes, no CPU readback** (SURVEY.md §7 hard part 2): every
  (src→dst) segment is padded to ``max_tokens`` slots.  The DEFAULT
  (``max_tokens=None``) is the lossless worst case ``t_loc * topk`` — the
  reference's ``MAX_M`` sizing (ep_a2a.py:353-387), no token is ever
  dropped.  Choosing a tighter capacity turns on standard capacity-factor
  truncation; that is never silent: dispatch returns the exact global
  dropped-assignment count alongside the payload.
* **Slot-addressed return routing**: the sender records (dest, slot) for
  every (token, k) assignment when packing; ``combine`` simply ships the
  expert outputs back through the inverse AllToAll — same slots, so no
  index metadata needs to travel back (the reference re-sends topk-id
  tables both ways).
* Expert ids ride as a tiny int32 side-channel AllToAll overlapping the
  payload one (the reference's separate splits/indices putmem).

Expert ownership: expert ``e`` lives on rank ``e // (n_experts // world)``
(contiguous blocks, the reference's layout).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.kernels.all_to_all import (
    AllToAllContext,
    _a2a_wire_block,
    fast_all_to_all_shard,
    fast_all_to_all_shard_diff,
)
from triton_dist_tpu.kernels.moe_utils import stable_rank_in_group
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

META_COLS = 8  # int32 metadata columns (col 0 = expert id), DMA-friendly pad


def ep_dispatch_shard(x_loc, experts_loc, *, axis, n_experts,
                      max_tokens=None, impl, interpret,
                      zero_undefined=False):
    """Pack per-destination-rank slots and shuffle tokens to expert owners.

    x_loc [t_loc, H], experts_loc [t_loc, topk] i32.  Routing weights are
    only needed at combine time.  ``max_tokens=None`` (the default) sizes
    every (src→dst) segment for the lossless worst case ``t_loc * topk``.
    Returns (recv [world, max_tokens, H], recv_expert [world, max_tokens]
    i32, recv_splits [world] i32, plan, n_dropped) where ``n_dropped`` is
    the GLOBAL (psum over ``axis``, replicated) count of (token, k)
    assignments truncated by capacity — always 0 at the default sizing.

    Under the splits-proportional a2a, recv rows beyond the last shipped
    block are UNDEFINED.  ``zero_undefined=True`` masks them to zero (one
    elementwise pass) — REQUIRED when recv feeds a differentiated matmul:
    weight gradients contract over all rows, and NaN garbage times a zero
    cotangent is NaN.  Inference paths that mask at combine can skip it.
    """
    world = jax.lax.axis_size(axis)
    t_loc, topk = experts_loc.shape
    hidden = x_loc.shape[1]
    epr = n_experts // world  # experts per rank
    n = t_loc * topk
    if max_tokens is None:
        max_tokens = n  # worst case: every assignment to one destination

    flat_e = experts_loc.reshape(-1)
    dest = flat_e // epr                                   # [n] dest rank
    # Slot within the destination group, stable by assignment order.
    slot, counts = stable_rank_in_group(dest, world)
    valid = slot < max_tokens

    token_of = jnp.arange(n) // topk
    dest_safe = jnp.where(valid, dest, world)  # OOB rows dropped by scatter
    send = jnp.zeros((world, max_tokens, hidden), x_loc.dtype)
    send = send.at[dest_safe, slot].set(x_loc[token_of], mode="drop")
    meta = jnp.zeros((world, max_tokens, META_COLS), jnp.int32)
    meta = meta.at[dest_safe, slot, 0].set(flat_e, mode="drop")
    splits = jnp.minimum(counts, max_tokens).astype(jnp.int32)
    n_dropped = jax.lax.psum(
        jnp.maximum(counts - max_tokens, 0).sum().astype(jnp.int32), axis)

    # Wire-block hint: the expected balanced load per (src->dst) segment
    # is n/world rows; a block larger than that is pure padding on the
    # wire (the lossless default max_tokens sizing is world x larger than
    # the balanced load by construction).
    wb = _a2a_wire_block(max_tokens, cap=n // world)
    recv, recv_splits = fast_all_to_all_shard_diff(
        send, splits, axis, impl, interpret, wb)
    recv_meta, _ = fast_all_to_all_shard(
        meta, splits, axis=axis, impl="xla", interpret=interpret)
    if zero_undefined:
        row = jax.lax.broadcasted_iota(jnp.int32, (world, max_tokens), 1)
        recv = jnp.where((row < recv_splits[:, None])[..., None], recv, 0)

    # Plan = (dest, slot, valid, recv_splits): a plain tuple so shard_map
    # out_specs stay hashable for the jit cache.  recv_splits rides along
    # so combine's return shuffle moves only the received rows (wire
    # bytes proportional to actual tokens, matching dispatch).
    return (recv, recv_meta[:, :, 0], recv_splits,
            (dest, slot, valid, recv_splits), n_dropped)


def ep_combine_shard(y, weights_loc, plan, *, axis, impl, interpret):
    """Inverse shuffle + topk-weighted reduce back to token order.

    y [world, max_tokens, H]: expert outputs in the *received* layout
    (block p returns to peer p, same slots).  Returns out [t_loc, H].
    """
    world, max_tokens, hidden = y.shape
    t_loc, topk = weights_loc.shape
    dest, slot, valid, recv_splits = plan
    # Send back exactly the rows received (every valid slot is < the
    # split count by construction); padded slots never touch the wire.
    wb = _a2a_wire_block(max_tokens, cap=(t_loc * topk) // world)
    back, _ = fast_all_to_all_shard_diff(y, recv_splits, axis, impl,
                                         interpret, wb)

    vals = back[jnp.minimum(dest, world - 1), jnp.minimum(slot, max_tokens - 1)]
    # Zero invalid slots BEFORE the weighted sum: with proportional
    # transfers the padded recv rows are undefined (not zeros), and
    # 0 * garbage could be NaN.
    vals = jnp.where(valid[:, None], vals, 0)
    w = (weights_loc.reshape(-1, 1) * valid[:, None]).astype(jnp.float32)
    out = (w * vals.astype(jnp.float32)).reshape(t_loc, topk, hidden).sum(axis=1)
    return out.astype(y.dtype)


@dataclass
class EPAll2AllLayer:
    """Reference analog: ``EPAll2AllLayer`` (ep_a2a_layer.py:40-240).

    Functional: ``dispatch`` returns a plan pytree that ``combine`` takes
    back, instead of mutating layer-owned symm buffers/signals (which a
    jit-traced TPU program cannot hold across calls anyway).
    """

    ctx: AllToAllContext
    n_experts: int
    topk: int

    def __post_init__(self):
        assert self.n_experts % self.ctx.world == 0, \
            (self.n_experts, self.ctx.world)

    @property
    def experts_per_rank(self) -> int:
        return self.n_experts // self.ctx.world

    def dispatch(self, x, experts):
        """x [T, H] P(axis); experts [T, topk] P(axis).

        Returns (recv_tokens — shard-stacked receive buffers P(axis),
        recv_expert, recv_splits, plan, n_dropped), where on each device the
        receive block is [world, max_tokens, H], ``recv_expert`` holds the
        global expert id of every valid received row, and ``n_dropped`` is
        the replicated global truncated-assignment count (0 unless
        ``ctx.max_tokens`` was set below the ``t_loc * topk`` worst case).
        """
        ctx = self.ctx
        fn = cached_shard_jit(
            ep_dispatch_shard,
            ctx.mesh,
            (P(ctx.axis), P(ctx.axis)),
            (P(ctx.axis), P(ctx.axis), P(ctx.axis),
             (P(ctx.axis), P(ctx.axis), P(ctx.axis), P(ctx.axis)), P()),
            axis=ctx.axis, n_experts=self.n_experts,
            max_tokens=ctx.max_tokens, impl=ctx.impl, interpret=ctx.interpret,
        )
        return fn(x, experts)

    def combine(self, y, weights, plan):
        """y: expert outputs in received layout, P(axis).  Returns [T, H]."""
        ctx = self.ctx
        fn = cached_shard_jit(
            ep_combine_shard,
            ctx.mesh,
            (P(ctx.axis), P(ctx.axis),
             (P(ctx.axis), P(ctx.axis), P(ctx.axis), P(ctx.axis))),
            P(ctx.axis),
            axis=ctx.axis, impl=ctx.impl, interpret=ctx.interpret,
        )
        return fn(y, weights, plan)
