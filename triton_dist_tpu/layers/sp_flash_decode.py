"""Sequence-parallel GQA flash-decode attention layer.

Reference analog: ``python/triton_dist/layers/nvidia/sp_flash_decode_layer.py``
(``SpGQAFlashDecodeAttention``, :43-184): local split-KV decode → LL allgather
of per-rank partials (out ⊕ lse packed) → inter-rank LSE combine, plus
management of the gather buffer and the KV cache.

TPU-native differences:
* No symm-buffer grow/shrink machinery (:111-132) — buffers are jax.Arrays
  sized by the call's shapes; XLA owns allocation.
* The KV cache is a sequence-sharded jax.Array; appending a decoded token is
  an owner-ranked dynamic-update inside shard_map (each rank updates only the
  rows it owns) instead of host-side index writes into a symmetric tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.flash_decode import (
    SpDecodeContext,
    create_sp_decode_context,
    quantize_kv,
    sp_gqa_decode,
    sp_gqa_decode_shard,
)
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


def _sp_decode_q_shard(q, kq, ks, vq, vs, kv_lens, *, axis, block_s, impl,
                       interpret, soft_cap=0.0, window=0):
    """Shard-level SP decode over an int8 cache (positional scales for
    shard_map)."""
    return sp_gqa_decode_shard(q, kq, vq, kv_lens, axis=axis,
                               block_s=block_s, impl=impl,
                               interpret=interpret, k_scale=ks, v_scale=vs,
                               soft_cap=soft_cap, window=window)


def append_kv_shard_q(kq, ks, vq, vs, new_k, new_v, kv_lens, *, axis):
    """Quantized twin of :func:`append_kv_shard`: the new rows quantize per
    (batch, head) before landing in the int8 cache + scale plane.  The
    scale planes reuse the same owner-rank write by riding through
    :func:`append_kv_shard` as D=1 caches."""
    nk_q, nk_s = quantize_kv(new_k)          # [B, Hkv, D] i8, [B, Hkv]
    nv_q, nv_s = quantize_kv(new_v)
    kq, vq = append_kv_shard(kq, vq, nk_q, nv_q, kv_lens, axis=axis)
    ks1, vs1 = append_kv_shard(ks[..., None], vs[..., None],
                               nk_s[..., None], nv_s[..., None], kv_lens,
                               axis=axis)
    return kq, ks1[..., 0], vq, vs1[..., 0]


def append_kv_shard(k_cache, v_cache, new_k, new_v, kv_lens, *, axis):
    """Per-device append of one token's K/V at global position ``kv_lens[b]``.

    k/v_cache: [B, Hkv, S_loc, D] (this rank's sequence shard);
    new_k/new_v: [B, Hkv, D]; kv_lens: [B] global lengths *before* append.
    Non-owner ranks rewrite the existing row (no-op by value).
    """
    s_loc = k_cache.shape[2]
    me = jax.lax.axis_index(axis)

    def per_batch(kc, vc, nk, nv, pos):
        # kc/vc: [Hkv, S_loc, D]; nk/nv: [Hkv, D]; pos: global scalar.
        lp = jnp.clip(pos - me * s_loc, 0, s_loc - 1)
        own = (pos >= me * s_loc) & (pos < (me + 1) * s_loc)

        def upd(cache, new):
            cur = jax.lax.dynamic_slice(
                cache, (0, lp, 0), (cache.shape[0], 1, cache.shape[2]))
            val = jnp.where(own, new[:, None, :].astype(cache.dtype), cur)
            return jax.lax.dynamic_update_slice(cache, val, (0, lp, 0))

        return upd(kc, nk), upd(vc, nv)

    return jax.vmap(per_batch)(k_cache, v_cache, new_k, new_v, kv_lens)


class SpGQAFlashDecodeAttention:
    """Decode-side sequence-parallel attention over a sharded KV cache.

    Usage (host level; arrays carry NamedShardings on ``ctx.mesh``):
        layer = SpGQAFlashDecodeAttention(mesh, axis="sp")
        k_cache, v_cache = layer.init_cache(B, Hkv, S, D, dtype)
        k_cache, v_cache = layer.append_kv(k_cache, v_cache, k_t, v_t, lens)
        out = layer(q, k_cache, v_cache, lens + 1)
    """

    def __init__(self, mesh: Mesh, axis: str = "sp", block_s: int | None = None,
                 impl: str = "auto", interpret: bool = False,
                 check_bounds: bool = True, kv_dtype=None,
                 soft_cap: float = 0.0, window: int = 0):
        # ``soft_cap``: Gemma-2 logit capping; ``window``: sliding-window
        # attention — the GLOBAL window rule at any world size (r5: each
        # shard intersects [kv_len - window, kv_len) with its range via
        # the unclipped window_lens; fully-outside shards emit lse = NEG
        # partials the combine ignores).  Threaded to every decode path
        # (reference analog: sp_flash_decode_layer.py:46).
        self.ctx: SpDecodeContext = create_sp_decode_context(
            mesh, axis=axis, block_s=block_s, impl=impl, interpret=interpret,
            soft_cap=soft_cap, window=window)
        # The append overflow guard costs a host sync per step (it reads
        # max(kv_lens)); hot decode loops tracking lengths host-side can
        # disable it.
        self.check_bounds = check_bounds
        # kv_dtype=jnp.int8 stores the cache quantized (symmetric per-row
        # int8 + a [B, Hkv, S] f32 scale plane): decode is bandwidth-bound,
        # so halving cache bytes is a direct speedup (docs/perf.md).
        assert kv_dtype in (None, jnp.int8), kv_dtype
        self.kv_dtype = kv_dtype

    @property
    def quantized(self) -> bool:
        return self.kv_dtype == jnp.int8

    @property
    def mesh(self) -> Mesh:
        return self.ctx.mesh

    @property
    def world(self) -> int:
        return self.ctx.world

    def cache_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(None, None, self.ctx.axis))

    def init_cache(self, batch: int, n_kv_heads: int, max_seq: int,
                   head_dim: int, dtype=jnp.bfloat16, k_init=None,
                   v_init=None):
        """Zeroed sequence-sharded K/V caches [B, Hkv, S, D]; when
        ``k_init``/``v_init`` [B, Hkv, S0, D] are given (the prefill KVs)
        they are written at positions [0, S0) — quantized on the way in for
        an int8 cache.

        Float caches are a (k, v) array pair; int8 caches are a pair of
        dicts ``{"q": int8 data, "s": f32 [B, Hkv, S] scales}``.  Both go
        through ``append_kv`` / ``__call__`` unchanged.
        """
        assert max_seq % self.world == 0, (max_seq, self.world)
        shape = (batch, n_kv_heads, max_seq, head_dim)
        sh = self.cache_sharding()

        def place(x):
            return jax.device_put(x, sh)

        if not self.quantized:
            k = jnp.zeros(shape, dtype)
            v = jnp.zeros(shape, dtype)
            if k_init is not None:
                k = jax.lax.dynamic_update_slice(
                    k, k_init.astype(dtype), (0, 0, 0, 0))
                v = jax.lax.dynamic_update_slice(
                    v, v_init.astype(dtype), (0, 0, 0, 0))
            return place(k), place(v)

        kq = jnp.zeros(shape, jnp.int8)
        vq = jnp.zeros(shape, jnp.int8)
        ks = jnp.ones(shape[:3], jnp.float32)
        vs = jnp.ones(shape[:3], jnp.float32)
        if k_init is not None:
            nkq, nks = quantize_kv(k_init)
            nvq, nvs = quantize_kv(v_init)
            kq = jax.lax.dynamic_update_slice(kq, nkq, (0, 0, 0, 0))
            vq = jax.lax.dynamic_update_slice(vq, nvq, (0, 0, 0, 0))
            ks = jax.lax.dynamic_update_slice(ks, nks, (0, 0, 0))
            vs = jax.lax.dynamic_update_slice(vs, nvs, (0, 0, 0))
        return ({"q": place(kq), "s": place(ks)},
                {"q": place(vq), "s": place(vs)})

    def append_kv(self, k_cache, v_cache, new_k, new_v, kv_lens):
        """Write one new token's K/V at position kv_lens[b] per batch row.

        Raises on cache overflow (pos >= max_seq) when ``kv_lens`` is
        concrete and ``check_bounds`` — otherwise no rank would own the row
        and the token would be silently dropped, leaving the next decode
        stale.
        """
        quantized = isinstance(k_cache, dict)
        assert quantized == self.quantized, (
            "cache/layer mismatch: layer kv_dtype="
            f"{self.kv_dtype} but cache is "
            f"{'quantized' if quantized else 'float'} — was this cache "
            "restored from a run with a different kv_dtype?")
        max_seq = (k_cache["q"] if quantized else k_cache).shape[2]
        if self.check_bounds and not isinstance(kv_lens, jax.core.Tracer):
            top = int(jnp.max(kv_lens))
            if top >= max_seq:
                raise ValueError(
                    f"KV cache overflow: append at position {top} but "
                    f"max_seq={max_seq}")
        seq = P(None, None, self.ctx.axis)
        if quantized:
            fn = cached_shard_jit(
                append_kv_shard_q,
                self.mesh,
                (seq, seq, seq, seq, P(), P(), P()),
                (seq, seq, seq, seq),
                axis=self.ctx.axis,
            )
            kq, ks, vq, vs = fn(k_cache["q"], k_cache["s"], v_cache["q"],
                                v_cache["s"], new_k, new_v, kv_lens)
            return {"q": kq, "s": ks}, {"q": vq, "s": vs}
        fn = cached_shard_jit(
            append_kv_shard,
            self.mesh,
            (seq, seq, P(), P(), P()),
            (seq, seq),
            axis=self.ctx.axis,
        )
        return fn(k_cache, v_cache, new_k, new_v, kv_lens)

    def __call__(self, q, k_cache, v_cache, kv_lens, block_table=None):
        """q [B, Hq, D] -> attention output [B, Hq, D] (replicated).

        With ``block_table`` [B, world * n_local] the caches are PAGED
        pools [world * N_loc, Hkv, page, D] (reference analog: the
        ``block_table`` argument of ``SpGQAFlashDecodeAttention.forward``,
        sp_flash_decode_layer.py:78): logical page i of batch b lives at
        pool row ``block_table[b, i]``, and rank r owns logical pages
        [r*n_local, (r+1)*n_local) whose entries must point into its pool
        shard [r*N_loc, (r+1)*N_loc).
        """
        if block_table is not None:
            assert not self.quantized, "paged int8 cache not supported yet"
            assert block_table.shape[1] % self.world == 0, (
                f"block_table columns {block_table.shape[1]} must divide "
                f"by world {self.world} (trailing logical pages would be "
                f"silently dropped)")
            assert k_cache.shape[0] % self.world == 0, (
                k_cache.shape, self.world)
            n_loc_pool = k_cache.shape[0] // self.world
            fn = cached_shard_jit(
                _sp_decode_paged_shard,
                self.mesh,
                (P(), P(self.ctx.axis), P(self.ctx.axis), P(), P()),
                P(),
                axis=self.ctx.axis, impl=self.ctx.impl,
                interpret=self.ctx.interpret, n_loc_pool=n_loc_pool,
                soft_cap=self.ctx.soft_cap, window=self.ctx.window,
            )
            return fn(q, k_cache, v_cache, block_table, kv_lens)
        assert isinstance(k_cache, dict) == self.quantized, (
            "cache/layer mismatch (see append_kv)")
        if isinstance(k_cache, dict):
            seq = P(None, None, self.ctx.axis)
            fn = cached_shard_jit(
                _sp_decode_q_shard,
                self.mesh,
                (P(), seq, seq, seq, seq, P()),
                P(),
                axis=self.ctx.axis, block_s=self.ctx.block_s,
                impl=self.ctx.impl, interpret=self.ctx.interpret,
                soft_cap=self.ctx.soft_cap, window=self.ctx.window,
            )
            return fn(q, k_cache["q"], k_cache["s"], v_cache["q"],
                      v_cache["s"], kv_lens)
        return sp_gqa_decode(q, k_cache, v_cache, kv_lens, self.ctx)

    # -- paged cache (block_table) ---------------------------------------

    def pool_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.ctx.axis))

    def init_paged_cache(self, batch: int, n_kv_heads: int, page: int,
                         pages_per_seq: int, head_dim: int,
                         dtype=jnp.bfloat16):
        """Zeroed page pools + a valid per-sequence block table.

        ``pages_per_seq`` (must divide by world) logical pages per
        sequence; each rank's pool shard holds ``batch * pages_per_seq /
        world`` pages so every (sequence, logical page) pair gets a
        DISTINCT pool row.  Returns (k_pool, v_pool, table): pools
        [world * N_loc, Hkv, page, D] sharded on the page axis, table
        [batch, pages_per_seq] int32 laid out so rank r owns logical
        pages [r*n/w, (r+1)*n/w) in its own shard rows.  A serving
        allocator may permute rows freely within each rank's ownership
        range."""
        assert pages_per_seq % self.world == 0, (pages_per_seq, self.world)
        n_seq_loc = pages_per_seq // self.world
        n_loc = batch * n_seq_loc
        shape = (self.world * n_loc, n_kv_heads, page, head_dim)
        sh = self.pool_sharding()
        pool_k = jax.device_put(jnp.zeros(shape, dtype), sh)
        pool_v = jax.device_put(jnp.zeros(shape, dtype), sh)
        # table[b, i] with i = r*n_seq_loc + j  ->  r*n_loc + b*n_seq_loc + j
        r = jnp.arange(pages_per_seq, dtype=jnp.int32) // n_seq_loc
        j = jnp.arange(pages_per_seq, dtype=jnp.int32) % n_seq_loc
        b = jnp.arange(batch, dtype=jnp.int32)[:, None]
        table = r[None] * n_loc + b * n_seq_loc + j[None]
        return pool_k, pool_v, table


def _sp_decode_paged_shard(q, k_pool, v_pool, table, kv_lens, *, axis,
                           impl, interpret, n_loc_pool, soft_cap=0.0,
                           window=0):
    """Shard body: slice this rank's table columns and rebase its entries
    into local pool coordinates."""
    from triton_dist_tpu.kernels.flash_decode import (
        sp_gqa_decode_paged_shard)

    me = jax.lax.axis_index(axis)
    n_local = table.shape[1] // jax.lax.axis_size(axis)
    local = jax.lax.dynamic_slice(
        table, (0, me * n_local), (table.shape[0], n_local))
    local = local - me * n_loc_pool
    return sp_gqa_decode_paged_shard(q, k_pool, v_pool, local, kv_lens,
                                     axis=axis, impl=impl,
                                     interpret=interpret,
                                     soft_cap=soft_cap, window=window)
