"""Sequence-parallel GQA flash-decode attention layer.

Reference analog: ``python/triton_dist/layers/nvidia/sp_flash_decode_layer.py``
(``SpGQAFlashDecodeAttention``, :43-184): local split-KV decode → LL allgather
of per-rank partials (out ⊕ lse packed) → inter-rank LSE combine, plus
management of the gather buffer and the KV cache.

TPU-native differences:
* No symm-buffer grow/shrink machinery (:111-132) — buffers are jax.Arrays
  sized by the call's shapes; XLA owns allocation.
* The KV cache is a sequence-sharded jax.Array; appending a decoded token is
  an owner-ranked dynamic-update inside shard_map (each rank updates only the
  rows it owns) instead of host-side index writes into a symmetric tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.flash_decode import (
    SpDecodeContext,
    create_sp_decode_context,
    sp_gqa_decode,
)
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


def append_kv_shard(k_cache, v_cache, new_k, new_v, kv_lens, *, axis):
    """Per-device append of one token's K/V at global position ``kv_lens[b]``.

    k/v_cache: [B, Hkv, S_loc, D] (this rank's sequence shard);
    new_k/new_v: [B, Hkv, D]; kv_lens: [B] global lengths *before* append.
    Non-owner ranks rewrite the existing row (no-op by value).
    """
    s_loc = k_cache.shape[2]
    me = jax.lax.axis_index(axis)

    def per_batch(kc, vc, nk, nv, pos):
        # kc/vc: [Hkv, S_loc, D]; nk/nv: [Hkv, D]; pos: global scalar.
        lp = jnp.clip(pos - me * s_loc, 0, s_loc - 1)
        own = (pos >= me * s_loc) & (pos < (me + 1) * s_loc)

        def upd(cache, new):
            cur = jax.lax.dynamic_slice(
                cache, (0, lp, 0), (cache.shape[0], 1, cache.shape[2]))
            val = jnp.where(own, new[:, None, :].astype(cache.dtype), cur)
            return jax.lax.dynamic_update_slice(cache, val, (0, lp, 0))

        return upd(kc, nk), upd(vc, nv)

    return jax.vmap(per_batch)(k_cache, v_cache, new_k, new_v, kv_lens)


class SpGQAFlashDecodeAttention:
    """Decode-side sequence-parallel attention over a sharded KV cache.

    Usage (host level; arrays carry NamedShardings on ``ctx.mesh``):
        layer = SpGQAFlashDecodeAttention(mesh, axis="sp")
        k_cache, v_cache = layer.init_cache(B, Hkv, S, D, dtype)
        k_cache, v_cache = layer.append_kv(k_cache, v_cache, k_t, v_t, lens)
        out = layer(q, k_cache, v_cache, lens + 1)
    """

    def __init__(self, mesh: Mesh, axis: str = "sp", block_s: int = 1024,
                 impl: str = "auto", interpret: bool = False,
                 check_bounds: bool = True):
        self.ctx: SpDecodeContext = create_sp_decode_context(
            mesh, axis=axis, block_s=block_s, impl=impl, interpret=interpret)
        # The append overflow guard costs a host sync per step (it reads
        # max(kv_lens)); hot decode loops tracking lengths host-side can
        # disable it.
        self.check_bounds = check_bounds

    @property
    def mesh(self) -> Mesh:
        return self.ctx.mesh

    @property
    def world(self) -> int:
        return self.ctx.world

    def cache_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(None, None, self.ctx.axis))

    def init_cache(self, batch: int, n_kv_heads: int, max_seq: int,
                   head_dim: int, dtype=jnp.bfloat16):
        """Zeroed sequence-sharded K/V caches [B, Hkv, S, D]."""
        assert max_seq % self.world == 0, (max_seq, self.world)
        shape = (batch, n_kv_heads, max_seq, head_dim)
        z = jnp.zeros(shape, dtype)
        sh = self.cache_sharding()
        return jax.device_put(z, sh), jax.device_put(z, sh)

    def append_kv(self, k_cache, v_cache, new_k, new_v, kv_lens):
        """Write one new token's K/V at position kv_lens[b] per batch row.

        Raises on cache overflow (pos >= max_seq) when ``kv_lens`` is
        concrete and ``check_bounds`` — otherwise no rank would own the row
        and the token would be silently dropped, leaving the next decode
        stale.
        """
        max_seq = k_cache.shape[2]
        if self.check_bounds and not isinstance(kv_lens, jax.core.Tracer):
            top = int(jnp.max(kv_lens))
            if top >= max_seq:
                raise ValueError(
                    f"KV cache overflow: append at position {top} but "
                    f"max_seq={max_seq}")
        fn = cached_shard_jit(
            append_kv_shard,
            self.mesh,
            (P(None, None, self.ctx.axis), P(None, None, self.ctx.axis),
             P(), P(), P()),
            (P(None, None, self.ctx.axis), P(None, None, self.ctx.axis)),
            axis=self.ctx.axis,
        )
        return fn(k_cache, v_cache, new_k, new_v, kv_lens)

    def __call__(self, q, k_cache, v_cache, kv_lens):
        """q [B, Hq, D] -> attention output [B, Hq, D] (replicated)."""
        return sp_gqa_decode(q, k_cache, v_cache, kv_lens, self.ctx)
