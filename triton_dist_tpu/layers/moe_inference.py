"""Serving-side distributed MoE layer: dispatch → grouped FFN → combine.

Reference analog: ``test/nvidia/test_ep_moe_inference.py``'s
``DistributedMoELayer`` (:337-492) — the inference composition of the EP
machinery: ``fast_all_to_all`` dispatch, token-sorted GroupGEMM expert
compute (``moe_groupgemm_kernel`` :171-231), inverse AllToAll combine with
an ``index_add_`` topk-reduce (:472-478).  The reference leaves activation
quant/scale stubs unimplemented (:492-506); here the expert MLP is a real
SwiGLU.

TPU-native composition (all pieces are the framework's own):

* dispatch/combine: ``layers/ep_a2a.py`` slot-addressed AllToAll over the
  low-latency kernel (static max-token padding, no CPU readback);
* expert compute: device-side sort/align (``kernels/moe_utils.py``) feeding
  the grouped Pallas GEMM (``kernels/group_gemm.py``);
* routing: either caller-provided (the reference's simulated indices) or an
  internal fp32 router.

Unlike the training path (models/moe.py) there is no aux loss and no VJP
requirement; one jitted shard program per (shape, dtype) serves any batch.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.group_gemm import moe_ffn_sorted
from triton_dist_tpu.kernels.moe_utils import (
    gather_sorted,
    sort_align,
    topk_routing,
)
from triton_dist_tpu.layers.ep_a2a import ep_combine_shard, ep_dispatch_shard
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


def moe_infer_shard(x_loc, weights_loc, experts_loc, w_gate, w_up, w_down, *,
                    axis, n_experts, max_tokens, block_m, impl, interpret):
    """One device's serving MoE FFN: x_loc [t_loc, H] → [t_loc, H].

    weights_loc [t_loc, topk] f32 routing weights, experts_loc [t_loc, topk]
    i32 global expert ids; w_* are this rank's expert slabs
    [epr, H, F] / [epr, F, H].
    """
    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    epr = n_experts // world
    hidden = x_loc.shape[1]

    recv, recv_expert, _splits, plan, _dropped = ep_dispatch_shard(
        x_loc, experts_loc, axis=axis, n_experts=n_experts,
        max_tokens=max_tokens, impl=impl, interpret=interpret)
    max_tokens = recv.shape[1]  # dispatch owns the None→worst-case rule

    # Sort received tokens by local expert and run the grouped SwiGLU.
    # Padding rows are undefined under the splits-proportional a2a (no
    # longer zero-filled); steering them to expert 0 is harmless — their
    # values never reach the output (combine zeroes invalid slots before
    # the weighted sum).
    T = world * max_tokens
    local_e = jnp.clip(recv_expert.reshape(T, 1) - me * epr, 0, epr - 1)
    splan = sort_align(local_e, epr, block_m)
    x_sorted = gather_sorted(recv.reshape(T, hidden), splan["dest"],
                             splan["m_pad"])
    y_sorted = moe_ffn_sorted(x_sorted, w_gate, w_up, w_down,
                              splan["tile_expert"], block_m=block_m,
                              impl=impl, interpret=interpret)
    y = y_sorted[splan["dest"]].reshape(world, max_tokens, hidden)

    return ep_combine_shard(y, weights_loc, plan, axis=axis, impl=impl,
                            interpret=interpret)


def moe_ffn_sorted_w8a8(x_sorted, wg_q, wg_s, wu_q, wu_s, wd_q, wd_s,
                        tile_expert, *, block_m, impl, interpret):
    """W8A8 grouped SwiGLU: dynamic per-row activation quant around exact
    int8 grouped GEMMs, per-expert-channel weight scales.

    x_sorted [M_pad, H] float; w*_q int8 stacks [epr, H, F] / [epr, F, H]
    with scales [epr, F] / [epr, H]; tile_expert [M_pad // block_m].
    """
    from triton_dist_tpu.kernels.group_gemm import group_gemm
    from triton_dist_tpu.kernels.quant import quantize_rowwise

    gg = functools.partial(group_gemm, tile_expert=tile_expert,
                           block_m=block_m, impl=impl, interpret=interpret)
    row_e = jnp.repeat(tile_expert, block_m)          # expert of each row

    x_q, x_s = quantize_rowwise(x_sorted)
    gate = gg(x_q, wg_q).astype(jnp.float32) * x_s[:, None] * wg_s[row_e]
    up = gg(x_q, wu_q).astype(jnp.float32) * x_s[:, None] * wu_s[row_e]
    hidden = jax.nn.silu(gate) * up
    h_q, h_s = quantize_rowwise(hidden)
    down = gg(h_q, wd_q).astype(jnp.float32) * h_s[:, None] * wd_s[row_e]
    return down.astype(x_sorted.dtype)


def moe_infer_shard_w8a8(x_loc, weights_loc, experts_loc, wg_q, wg_s, wu_q,
                         wu_s, wd_q, wd_s, *, axis, n_experts, max_tokens,
                         block_m, impl, interpret):
    """W8A8 twin of :func:`moe_infer_shard` (same dispatch/combine; the
    expert compute runs the int8 grouped GEMMs)."""
    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    epr = n_experts // world
    hidden = x_loc.shape[1]

    recv, recv_expert, _splits, plan, _dropped = ep_dispatch_shard(
        x_loc, experts_loc, axis=axis, n_experts=n_experts,
        max_tokens=max_tokens, impl=impl, interpret=interpret)
    max_tokens = recv.shape[1]  # dispatch owns the None→worst-case rule

    T = world * max_tokens
    local_e = jnp.clip(recv_expert.reshape(T, 1) - me * epr, 0, epr - 1)
    splan = sort_align(local_e, epr, block_m)
    x_sorted = gather_sorted(recv.reshape(T, hidden), splan["dest"],
                             splan["m_pad"])
    y_sorted = moe_ffn_sorted_w8a8(
        x_sorted, wg_q, wg_s, wu_q, wu_s, wd_q, wd_s,
        splan["tile_expert"], block_m=block_m, impl=impl,
        interpret=interpret)
    y = y_sorted[splan["dest"]].reshape(world, max_tokens, hidden)

    return ep_combine_shard(y, weights_loc, plan, axis=axis, impl=impl,
                            interpret=interpret)


@dataclass
class DistributedMoELayer:
    """Reference analog: ``DistributedMoELayer`` (test_ep_moe_inference.py:337).

    Expert weights are EP-sharded over ``axis`` (expert ``e`` on rank
    ``e // (E // world)``); tokens arrive sharded over the same axis.
    ``max_tokens`` is the per-(src→dst) capacity; the lossless worst case is
    ``t_loc * topk`` (the reference's ``MAX_M`` sizing, :443).
    """

    mesh: Mesh
    n_experts: int
    topk: int
    hidden: int
    intermediate: int
    max_tokens: int | None = None
    axis: str = "ep"
    # None = load-aware: the largest of {128, 256, 512} the balanced
    # per-expert token load sustains (512 is the measured ~87%-MFU
    # winner for dense loads; 128 was costing up to half the grouped
    # MFU — docs/perf.md, VERDICT r3 #4).
    block_m: int | None = None
    dtype: Any = jnp.bfloat16
    impl: str = "auto"
    interpret: bool = False
    weights: dict = field(default=None)

    def __post_init__(self):
        # axis may be one mesh axis or a (slow, fast) tuple — the latter
        # routes dispatch/combine through the two-tier AllToAll
        # (kernels/hierarchical.py); world is the product either way.
        axes = (self.axis,) if isinstance(self.axis, str) else self.axis
        self.world = int(np.prod([self.mesh.shape[a] for a in axes]))
        assert self.n_experts % self.world == 0, (self.n_experts, self.world)

    @property
    def experts_per_rank(self) -> int:
        return self.n_experts // self.world

    # -- weights -----------------------------------------------------------
    def weight_specs(self) -> dict:
        return {"router": P(),
                "w_gate": P(self.axis, None, None),
                "w_up": P(self.axis, None, None),
                "w_down": P(self.axis, None, None)}

    def init_weights(self, key) -> dict:
        """Random EP-sharded weights (the reference's torch.randn init)."""
        E, H, F = self.n_experts, self.hidden, self.intermediate
        ks = jax.random.split(key, 4)
        w = {
            "router": jax.random.normal(ks[0], (H, E), jnp.float32)
            / jnp.sqrt(jnp.float32(H)),
            "w_gate": (jax.random.normal(ks[1], (E, H, F), jnp.float32)
                       / jnp.sqrt(jnp.float32(H))).astype(self.dtype),
            "w_up": (jax.random.normal(ks[2], (E, H, F), jnp.float32)
                     / jnp.sqrt(jnp.float32(H))).astype(self.dtype),
            "w_down": (jax.random.normal(ks[3], (E, F, H), jnp.float32)
                       / jnp.sqrt(jnp.float32(F))).astype(self.dtype),
        }
        specs = self.weight_specs()
        self.weights = jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(self.mesh, s)),
            w, specs)
        return self.weights

    def quantize_weights(self) -> dict:
        """Convert the expert stacks to W8A8 (int8 + per-expert-channel
        scales); subsequent ``forward`` calls run the int8 grouped GEMMs.
        The router stays fp32 (routing is precision-sensitive)."""
        from triton_dist_tpu.kernels.quant import quantize_channelwise

        def per_expert(w):  # [E, K, N] → ([E, K, N] i8, [E, N] f32)
            qs = [quantize_channelwise(w[e]) for e in range(w.shape[0])]
            return (jnp.stack([q for q, _ in qs]),
                    jnp.stack([s for _, s in qs]))

        w = self.weights
        gq, gs = per_expert(w["w_gate"])
        uq, us = per_expert(w["w_up"])
        dq, ds = per_expert(w["w_down"])
        qw = {"router": w["router"],
              "w_gate_q": gq, "w_gate_s": gs,
              "w_up_q": uq, "w_up_s": us,
              "w_down_q": dq, "w_down_s": ds}
        ep = P(self.axis, None, None)
        sp = P(self.axis, None)
        specs = {"router": P(), "w_gate_q": ep, "w_gate_s": sp,
                 "w_up_q": ep, "w_up_s": sp,
                 "w_down_q": ep, "w_down_s": sp}
        self.weights = jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(self.mesh, s)),
            qw, specs)
        return self.weights

    @property
    def is_quantized(self) -> bool:
        return self.weights is not None and "w_gate_q" in self.weights

    # -- forward -----------------------------------------------------------
    def route(self, x) -> tuple[jax.Array, jax.Array]:
        """Router probabilities → (weights [T, topk] f32, experts i32)."""
        logits = jnp.dot(jnp.asarray(x, jnp.float32), self.weights["router"])
        return topk_routing(logits, self.topk)

    def forward(self, x, experts=None, routing_weights=None) -> jax.Array:
        """x [T, H] sharded P(axis).  ``experts``/``routing_weights`` may be
        given (the reference's simulated indices) or come from the router.
        Returns [T, H] sharded P(axis)."""
        if experts is None:
            routing_weights, experts = self.route(x)
        if routing_weights is None:
            routing_weights = jnp.full(experts.shape, 1.0 / self.topk,
                                       jnp.float32)
        ax = self.axis
        from triton_dist_tpu.kernels.group_gemm import load_aware_block_m

        block_m = self.block_m or load_aware_block_m(
            x.shape[0] * self.topk, self.n_experts)
        opts = dict(axis=ax, n_experts=self.n_experts,
                    max_tokens=self.max_tokens, block_m=block_m,
                    impl=self.impl, interpret=self.interpret)
        ep = P(ax, None, None)
        sp = P(ax, None)
        if self.is_quantized:
            fn = cached_shard_jit(
                moe_infer_shard_w8a8, self.mesh,
                (P(ax), P(ax), P(ax), ep, sp, ep, sp, ep, sp),
                P(ax), **opts)
            w = self.weights
            return fn(x.astype(self.dtype), routing_weights, experts,
                      w["w_gate_q"], w["w_gate_s"], w["w_up_q"],
                      w["w_up_s"], w["w_down_q"], w["w_down_s"])
        fn = cached_shard_jit(
            moe_infer_shard, self.mesh,
            (P(ax), P(ax), P(ax), ep, ep, ep),
            P(ax), **opts)
        return fn(x.astype(self.dtype), routing_weights, experts,
                  self.weights["w_gate"], self.weights["w_up"],
                  self.weights["w_down"])

    __call__ = forward
