"""SPMD pipeline parallelism: a GPipe schedule under shard_map.

Idiomatic TPU PP (the scaling-book recipe): every stage runs the SAME
program; layer parameters are stacked along a leading layer axis and
sharded over the ``pp`` mesh axis, so each stage holds a contiguous block
of layers and applies them with ``lax.scan``.  The schedule is a single
``lax.scan`` over ``n_micro + n_stages - 1`` ticks; at every tick each
stage processes one microbatch-carry and hands it to the next stage with
``jax.lax.ppermute`` (XLA lowers this to an ICI collective-permute that
overlaps with the next tick's compute).  Bubbles execute as masked garbage
— inherent to SPMD GPipe, cost (n_stages-1)/(n_micro+n_stages-1).

The backward pipeline needs no code: ``jax.grad`` through the scan +
ppermute produces the reverse schedule (ppermute's transpose is the
inverse permutation), with activations rematerialized per jax defaults or
``jax.checkpoint`` on the block fn.

The carry is a pytree, so models thread auxiliary state (e.g. the MoE
load-balance loss) alongside activations through the pipe.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def stack_layer_params(layers: list[dict]) -> dict:
    """[{leaf: arr}, ...] per-layer dicts → {leaf: arr[L, ...]} stacked.

    The stacked leading axis is what gets sharded over the ``pp`` mesh axis
    (spec ``P("pp", ...)``); inside a stage it is the ``lax.scan`` axis.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def pipeline_spmd(
    block_fn: Callable[[Any, Any], Any],
    stage_params: Any,
    xs: Any,
    *,
    axis: str,
    n_micro: int,
):
    """Run the GPipe schedule.  Call inside shard_map.

    block_fn(layer_params, carry) -> carry: one layer applied to one
    microbatch carry (a pytree; leaves shaped [mb, ...]-like).
    stage_params: this stage's stacked layer block ({leaf: [L_loc, ...]}).
    xs: input carries, a pytree with leading [n_micro] on every leaf —
    consumed by stage 0 (other stages receive from their left neighbor).

    Returns the last stage's output carries ([n_micro] leading) — garbage
    on every other stage; mask with ``jax.lax.axis_index(axis) ==
    jax.lax.axis_size(axis) - 1`` (scalars from it are typically folded
    into a psum'd loss).
    """
    stage = jax.lax.axis_index(axis)
    n_stages = jax.lax.axis_size(axis)
    total = n_micro + n_stages - 1

    def apply_stage(carry):
        def body(c, layer):
            return block_fn(layer, c), None
        out, _ = jax.lax.scan(body, carry, stage_params)
        return out

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    zero_carry = jax.tree.map(lambda l: jnp.zeros_like(l[0]), xs)
    outs0 = jax.tree.map(
        lambda l: jnp.zeros((n_micro,) + l.shape[1:], l.dtype), xs)

    def tick(state, t):
        carry_in, outs = state
        m_in = jnp.clip(t, 0, n_micro - 1)
        x_t = jax.tree.map(
            lambda l: jax.lax.dynamic_index_in_dim(l, m_in, 0,
                                                   keepdims=False), xs)
        inp = jax.tree.map(
            lambda a, b: jnp.where(stage == 0, a, b), x_t, carry_in)
        y = apply_stage(inp)

        # Last stage finished microbatch m = t - (n_stages - 1) at this tick.
        m_out = t - (n_stages - 1)
        valid = m_out >= 0  # (m_out < n_micro holds: t <= total-1)
        slot = jnp.clip(m_out, 0, n_micro - 1)

        def stash(buf, val):
            cur = jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
            new = jnp.where(valid, val, cur)
            return jax.lax.dynamic_update_index_in_dim(buf, new, slot, 0)

        outs = jax.tree.map(stash, outs, y)
        carry_out = jax.tree.map(
            lambda l: jax.lax.ppermute(l, axis, perm), y)
        return (carry_out, outs), None

    (_, outs), _ = jax.lax.scan(
        tick, (zero_carry, outs0), jnp.arange(total))
    return outs
