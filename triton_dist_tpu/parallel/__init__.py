"""Parallelism schedules above the kernel layer.

The reference is a kernel library: DP and PP are explicitly absent there
(SURVEY.md §2.5 — "DP and PP are not implemented in the reference; the
building blocks are").  The TPU build supplies them: data parallelism is a
mesh axis + gradient psum (models/*.make_train_step), and pipeline
parallelism lives here as an SPMD GPipe schedule over a mesh axis
(``pipeline.py``), composing under one ``shard_map`` with the TP/SP/EP
kernels below it.
"""

from triton_dist_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_spmd,
    stack_layer_params,
)
