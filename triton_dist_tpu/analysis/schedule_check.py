"""Symbolic execution of a :class:`~.comm_schedule.CommSchedule` —
the race/deadlock checker.

The simulator runs every rank's op list round-robin with EAGER delivery
(DMAs land and signals arrive the instant they are issued — the most
permissive timing, so any blocking it finds is a true deadlock) while
tracking vector clocks for the adversarial-timing questions eager
execution alone cannot answer: an event is safe only if a
happens-before chain *forces* its ordering, not if this particular
interleaving happened to produce it.

HB edges: program order on each rank; a semaphore wait joins the clock
of every signal/DMA whose credit it consumed (FIFO per (rank, sem) —
the byte-counted TPU semantics); a DMA's landing write becomes visible
only at the wait that consumed its arrival credit.  On top of that:

- **deadlock** — round-robin progress stalls with unfinished ranks;
- **stranded credit** — any semaphore nonzero at exit, or any send
  never drained (the ``quiet`` contract);
- **read races** — a read (or a send's source read) that can observe a
  write not HB-ordered before it, a never-written slot, or data whose
  label is not the one the schedule owes that step (a swapped slot is a
  label mismatch here, not silent corruption on hardware);
- **write races** — a DMA landing on a slot whose previous write or
  read is not HB-ordered before the DMA's issue (the credit-semaphore
  backpressure is exactly what creates these chains);
- **write-once** — every declared output tile finalized exactly once
  on every rank;
- **slot-map bijectivity** — each declared step map is a permutation of
  ranks.

The seeded **mutation self-test** (:func:`mutation_self_test`) corrupts
schedules one op at a time — dropped signal, swapped slot, doubled
wait, double-written tile — and asserts the checker reports each class:
the checker checks the kernels, the mutations check the checker.
"""

from __future__ import annotations

import copy
import dataclasses
import random
import zlib
from collections import deque

from triton_dist_tpu.analysis.comm_schedule import (
    SCHEDULE_BUILDERS,
    CommSchedule,
    Op,
    build_schedule,
)

#: Schedule corruption classes the self-test must prove are caught.
MUTATIONS = ("drop_signal", "swap_slot", "double_wait", "double_write")


@dataclasses.dataclass
class ScheduleViolation:
    kind: str
    rank: int
    detail: str

    def __str__(self):
        return f"[{self.kind}] rank {self.rank}: {self.detail}"


class _Clock:
    """Vector clock over ``world`` ranks."""

    __slots__ = ("v",)

    def __init__(self, world=None, v=None):
        self.v = list(v) if v is not None else [0] * world

    def copy(self):
        return _Clock(v=self.v)

    def tick(self, rank):
        self.v[rank] += 1

    def join(self, other):
        self.v = [max(a, b) for a, b in zip(self.v, other.v)]

    def __le__(self, other):
        return all(a <= b for a, b in zip(self.v, other.v))


class _Dma:
    """One in-flight (issued) DMA."""

    __slots__ = ("src", "dst", "label", "ssem", "issue_clock",
                 "drained_clock", "op")

    def __init__(self, src, dst, label, ssem, issue_clock, op):
        self.src = src            # (rank, buf, slot)
        self.dst = dst            # (rank, buf, slot)
        self.label = label
        self.ssem = ssem
        self.issue_clock = issue_clock
        self.drained_clock = None  # set by the wait consuming the ssem
        self.op = op


class _WriteEv:
    __slots__ = ("label", "final", "avail_clock", "issue_clock", "seq",
                 "via")

    def __init__(self, label, final, avail_clock, issue_clock, seq, via):
        self.label = label
        self.final = final
        #: clock at which the write is ORDERED (local write: the writer
        #: op's clock; DMA landing: the consuming wait's clock, None
        #: until consumed)
        self.avail_clock = avail_clock
        self.issue_clock = issue_clock
        self.seq = seq
        self.via = via            # "local" | "dma"


class _Sim:
    def __init__(self, sched: CommSchedule):
        self.s = sched
        w = sched.world
        self.world = w
        self.clocks = [_Clock(w) for _ in range(w)]
        self.pc = [0] * w
        # (rank, sem) -> deque of credit events (clock, dma | None)
        self.sems: dict = {}
        # (rank, buf, slot) -> list[_WriteEv]
        self.writes: dict = {}
        # (rank, buf, slot) -> list[(clock, seq)] of reads
        self.reads: dict = {}
        # (rank, buf, slot) -> list[_Dma] sourced from there
        self.src_dmas: dict = {}
        self.violations: list[ScheduleViolation] = []
        self.seq = 0
        zero = _Clock(w)
        for rank, buf, slot, label in sched.init:
            self.writes.setdefault((rank, buf, slot), []).append(
                _WriteEv(label, False, zero.copy(), zero.copy(), -1,
                         "init"))

    # -- helpers ----------------------------------------------------------

    def _q(self, rank, sem):
        return self.sems.setdefault((rank, sem), deque())

    def _report(self, kind, rank, detail):
        self.violations.append(ScheduleViolation(kind, rank, detail))

    def _visible_write(self, rank, buf, slot, clock, op, *, what):
        """Latest HB-ordered write of (rank, buf, slot); reports races
        against unordered writes and unwritten slots."""
        evs = self.writes.get((rank, buf, slot), [])
        visible = None
        for ev in evs:
            if ev.avail_clock is not None and ev.avail_clock <= clock:
                if visible is None or ev.seq > visible.seq:
                    visible = ev
            else:
                self._report(
                    "race-read", rank,
                    f"{what} of {buf}[{slot}] at step {op.step} may "
                    f"observe an un-ordered in-flight write "
                    f"({ev.via}, label={ev.label}) — no happens-before "
                    f"chain orders the write before this read")
        if visible is None:
            self._report(
                "unwritten-read", rank,
                f"{what} of {buf}[{slot}] at step {op.step} observes no "
                f"completed write at all")
        return visible

    def _record_read(self, rank, buf, slot, clock):
        self.reads.setdefault((rank, buf, slot), []).append(
            (clock.copy(), self.seq))

    def _apply_write(self, rank, buf, slot, label, final, avail, issue,
                     via, issuer_rank, op):
        key = (rank, buf, slot)
        for ev in self.writes.get(key, []):
            ordered = (ev.avail_clock is not None
                       and ev.avail_clock <= issue)
            if not ordered:
                self._report(
                    "race-write", issuer_rank,
                    f"write into rank {rank} {buf}[{slot}] (step "
                    f"{op.step}, label={label}) races a prior "
                    f"{ev.via} write (label={ev.label}): no chain "
                    f"orders the old write's consumption before the "
                    f"new write's issue")
        for (rclock, _rseq) in self.reads.get(key, []):
            if not rclock <= issue:
                self._report(
                    "race-write", issuer_rank,
                    f"write into rank {rank} {buf}[{slot}] (step "
                    f"{op.step}, label={label}) races a prior read: "
                    f"the reader holds no credit chain ordering its "
                    f"read before this write")
        ev = _WriteEv(label, final, avail, issue, self.seq, via)
        self.writes.setdefault(key, []).append(ev)
        return ev

    # -- one op -----------------------------------------------------------

    def _try_op(self, rank, op: Op) -> bool:
        """Execute op on rank if possible; False = blocked."""
        clock = self.clocks[rank]
        if op.kind == "wait":
            q = self._q(rank, op.sem)
            if len(q) < op.count:
                return False
            self.seq += 1
            clock.tick(rank)
            for _ in range(op.count):
                cclock, dma = q.popleft()
                clock.join(cclock)
                if dma is not None:
                    if dma.dst is not None and dma.dst[0] == rank and \
                            op.sem != dma.ssem:
                        # arrival credit: the landing write becomes
                        # ordered at this wait
                        for ev in self.writes.get(dma.dst, []):
                            if ev.via == "dma" and ev.avail_clock is None \
                                    and ev.issue_clock is dma.issue_clock:
                                ev.avail_clock = clock.copy()
                    if op.sem == dma.ssem and dma.src[0] == rank:
                        dma.drained_clock = clock.copy()
            return True

        self.seq += 1
        clock.tick(rank)
        if op.kind == "signal":
            dst = op.dst if op.dst >= 0 else rank
            q = self._q(dst, op.sem)
            for _ in range(op.count):
                q.append((clock.copy(), None))
        elif op.kind == "write":
            # a local write must not clobber an in-flight DMA's source
            for dma in self.src_dmas.get((rank, op.buf, op.slot), []):
                if dma.ssem and (dma.drained_clock is None
                                 or not dma.drained_clock <= clock):
                    self._report(
                        "race-write", rank,
                        f"local write of {op.buf}[{op.slot}] at step "
                        f"{op.step} overwrites the source of an "
                        f"undrained DMA (label={dma.label})")
            self._apply_write(rank, op.buf, op.slot, op.label, op.final,
                              clock.copy(), clock.copy(), "local", rank,
                              op)
        elif op.kind == "read":
            vis = self._visible_write(rank, op.buf, op.slot, clock, op,
                                      what="read")
            self._record_read(rank, op.buf, op.slot, clock)
            if vis is not None and op.label is not None and \
                    vis.label != op.label:
                self._report(
                    "stale-read", rank,
                    f"read of {op.buf}[{op.slot}] at step {op.step} "
                    f"expects {op.label} but the slot holds "
                    f"{vis.label} — wrong tile consumed")
        elif op.kind == "send":
            # source read (the DMA engine reads src until drained)
            vis = self._visible_write(rank, op.src_buf, op.src_slot,
                                      clock, op, what="DMA source read")
            self._record_read(rank, op.src_buf, op.src_slot, clock)
            if vis is not None and op.label is not None and \
                    vis.label != op.label:
                self._report(
                    "stale-read", rank,
                    f"send from {op.src_buf}[{op.src_slot}] at step "
                    f"{op.step} ships {vis.label} where the schedule "
                    f"owes {op.label}")
            dst_rank = op.dst if op.dst >= 0 else rank
            issue = clock.copy()
            dma = _Dma((rank, op.src_buf, op.src_slot),
                       (dst_rank, op.buf, op.slot), op.label, op.ssem,
                       issue, op)
            self.src_dmas.setdefault(
                (rank, op.src_buf, op.src_slot), []).append(dma)
            # eager landing: write applied now, ordered only once the
            # receiver consumes the arrival credit
            self._apply_write(dst_rank, op.buf, op.slot, op.label,
                              op.final, None, issue, "dma", rank, op)
            self._q(dst_rank, op.rsem).append((issue, dma))
            if op.ssem:
                self._q(rank, op.ssem).append((issue, dma))
        else:
            raise ValueError(f"unknown op kind {op.kind!r}")
        return True

    # -- drive ------------------------------------------------------------

    def run(self):
        s = self.s
        while True:
            progressed = False
            done = 0
            for r in range(self.world):
                ops = s.ranks[r]
                while self.pc[r] < len(ops):
                    if self._try_op(r, ops[self.pc[r]]):
                        self.pc[r] += 1
                        progressed = True
                    else:
                        break
                if self.pc[r] >= len(ops):
                    done += 1
            if done == self.world:
                return True
            if not progressed:
                for r in range(self.world):
                    if self.pc[r] < len(s.ranks[r]):
                        op = s.ranks[r][self.pc[r]]
                        have = len(self._q(r, op.sem))
                        self._report(
                            "deadlock", r,
                            f"blocked at step {op.step} waiting "
                            f"{op.count} on '{op.sem}' (holds {have}"
                            f"{', ' + op.note if op.note else ''})")
                return False

    def finish_checks(self):
        s = self.s
        # stranded credits / undrained sends
        for (rank, sem), q in sorted(self.sems.items()):
            if q:
                self._report(
                    "stranded-credit", rank,
                    f"semaphore '{sem}' holds {len(q)} unconsumed "
                    f"credit(s) at kernel exit")
        for dmas in self.src_dmas.values():
            for dma in dmas:
                if dma.ssem and dma.drained_clock is None:
                    self._report(
                        "undrained-send", dma.src[0],
                        f"send of {dma.label} from "
                        f"{dma.src[1]}[{dma.src[2]}] never drained "
                        f"(the quiet contract)")
        # write-once outputs
        for buf, nslots in s.outputs.items():
            for rank in range(self.world):
                for slot in range(nslots):
                    finals = [ev for ev in
                              self.writes.get((rank, buf, slot), [])
                              if ev.final]
                    if len(finals) != 1:
                        self._report(
                            "write-once", rank,
                            f"output {buf}[{slot}] finalized "
                            f"{len(finals)} times (expected exactly 1)")
        # slot-map bijectivity
        for step, slots in sorted(s.slot_maps.items()):
            if sorted(slots) != list(range(self.world)):
                self._report(
                    "slot-map", -1,
                    f"step {step} slot map {slots} is not a bijection "
                    f"on ranks 0..{self.world - 1}")


def check_schedule(sched: CommSchedule) -> list[ScheduleViolation]:
    """Run every check; [] means the schedule is provably clean under
    any timing the happens-before relation admits."""
    sim = _Sim(sched)
    sim.run()
    sim.finish_checks()
    return sim.violations


def check_kernel(kernel: str, worlds=range(2, 33)) -> dict:
    """Convenience sweep: kernel x world sizes -> violation summary."""
    out = {"kernel": kernel, "worlds": [], "violations": []}
    for w in worlds:
        v = check_schedule(build_schedule(kernel, w))
        out["worlds"].append(w)
        out["violations"] += [f"world={w} {x}" for x in v]
    return out


# ---------------------------------------------------------------------------
# Mutations: the checker's own test harness
# ---------------------------------------------------------------------------


def mutate(sched: CommSchedule, kind: str,
           rng: random.Random) -> CommSchedule:
    """Return a deep-copied schedule corrupted by one seeded mutation of
    class ``kind`` (:data:`MUTATIONS`).  Raises ValueError when the
    schedule has no site for the class (the self-test skips those)."""
    m = copy.deepcopy(sched)
    m.kernel = f"{sched.kernel}+{kind}"
    if kind == "drop_signal":
        # dropped arrival: a signal op if any, else a send (its landing
        # write AND its arrival credit vanish together, exactly like a
        # producer that forgot to notify)
        sites = [(r, i) for r in range(m.world)
                 for i, op in enumerate(m.ranks[r])
                 if op.kind == "signal"]
        if not sites:
            sites = [(r, i) for r in range(m.world)
                     for i, op in enumerate(m.ranks[r])
                     if op.kind == "send"]
        if not sites:
            raise ValueError("no signal/send to drop")
        r, i = rng.choice(sites)
        del m.ranks[r][i]
    elif kind == "swap_slot":
        # a consumed slot / landing slot / DMA source slot / slot-map
        # entry points at the wrong tile
        sites = []
        for r in range(m.world):
            for i, op in enumerate(m.ranks[r]):
                if op.kind == "read":
                    sites.append(("dst", r, i))
                elif op.kind == "send":
                    sites.append(("dst", r, i))
                    sites.append(("src", r, i))
        for step in m.slot_maps:
            if m.world >= 2:
                sites.append(("map", step, -1))
        if not sites:
            raise ValueError("no slot to swap")

        def _nslots(buf):
            return max(
                [o.slot for rr in m.ranks for o in rr
                 if o.kind in ("send", "write", "read")
                 and o.buf == buf]
                + [o.src_slot for rr in m.ranks for o in rr
                   if o.kind == "send" and o.src_buf == buf]
                + [0]) + 1

        what, a, b = rng.choice(sites)
        if what == "map":
            slots = m.slot_maps[a]
            j = rng.randrange(len(slots))
            slots[j] = slots[(j + 1) % len(slots)]   # duplicate entry
        elif what == "src":
            op = m.ranks[a][b]
            op.src_slot = (op.src_slot + 1) % max(_nslots(op.src_buf), 2)
        else:
            op = m.ranks[a][b]
            op.slot = (op.slot + 1) % max(_nslots(op.buf), 2)
    elif kind == "double_wait":
        sites = [(r, i) for r in range(m.world)
                 for i, op in enumerate(m.ranks[r])
                 if op.kind == "wait"]
        if not sites:
            raise ValueError("no wait to double")
        r, i = rng.choice(sites)
        m.ranks[r][i].count *= 2
    elif kind == "double_write":
        sites = [(r, i) for r in range(m.world)
                 for i, op in enumerate(m.ranks[r])
                 if op.final and op.kind in ("write", "send")]
        if not sites:
            raise ValueError("no final write to double")
        r, i = rng.choice(sites)
        m.ranks[r].insert(i + 1, copy.deepcopy(m.ranks[r][i]))
    else:
        raise ValueError(f"unknown mutation {kind!r}; "
                         f"choose from {MUTATIONS}")
    return m


def mutation_self_test(kernels=None, worlds=(2, 3, 4), seeds=range(4),
                       ) -> dict:
    """Seeded corruption sweep: for every kernel x world x seed x
    mutation class, corrupt the schedule and assert the checker
    reports >= 1 violation.  Returns the tally; raises AssertionError
    naming the first silent corruption (a checker hole)."""
    kernels = sorted(SCHEDULE_BUILDERS) if kernels is None else kernels
    tally = {k: 0 for k in MUTATIONS}
    for kernel in kernels:
        for world in worlds:
            clean = build_schedule(kernel, world)
            base = check_schedule(clean)
            assert not base, (
                f"{kernel} world={world} not clean before mutation: "
                f"{[str(v) for v in base]}")
            for kind in MUTATIONS:
                for seed in seeds:
                    # stable site selection: crc32, not hash() — the
                    # salted builtin would pick different corruption
                    # sites every process, making a checker-hole
                    # failure unreplayable (the very class the
                    # no-unseeded-randomness rule bans)
                    salt = zlib.crc32(
                        f"{kernel}/{world}/{kind}".encode())
                    rng = random.Random(salt * 1000 + seed)
                    try:
                        bad = mutate(clean, kind, rng)
                    except ValueError:
                        continue
                    got = check_schedule(bad)
                    assert got, (
                        f"checker hole: {kind} on {kernel} "
                        f"world={world} seed={seed} was NOT caught")
                    tally[kind] += 1
    for kind, n in tally.items():
        assert n > 0, f"mutation class {kind} never had a site"
    return tally
