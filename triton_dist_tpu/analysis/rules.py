"""The source-lint rule registry — grep meta-tests, promoted.

Tier-1 grew several "meta-tests" that lint the source tree instead of
running it: annotation coverage over the kernel entry points, the
trace-taxonomy closure (every ``FinishReason`` and every ``.fire()``
seam has a registered event).  Those assertions now live HERE as
registered rules — one registry, one violation type, one waiver
mechanism — consumed three ways: the original tests call
:func:`run_rule` (same assertions, same failures), ``scripts/
lint_dist.py`` runs the whole registry as a CLI gate (JSON report,
nonzero exit on unwaived violation), and ``bench.py`` stamps the
verdict into the bench artifact.

A rule is a zero-argument callable returning ``list[Violation]``;
register with ``@rule("name")``.  Waivers (``LINT_WAIVERS.json`` at the
repo root) suppress KNOWN violations with a recorded justification —
every waiver must keep matching a live violation or it is reported
stale (so fixed code sheds its waiver instead of keeping a hole open).
"""

from __future__ import annotations

import ast
import dataclasses
import glob
import json
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(REPO, "triton_dist_tpu")
_KERNELS_DIR = os.path.join(_SRC, "kernels")

#: Default waiver file (docs/analysis.md "Waivers").
WAIVERS_PATH = os.path.join(REPO, "LINT_WAIVERS.json")

#: name -> rule callable; populated by :func:`rule`.
RULES: dict = {}


@dataclasses.dataclass
class Violation:
    rule: str
    message: str
    path: str = ""      # repo-relative file, "" for non-file rules
    line: int = 0
    waived: bool = False
    waiver_reason: str = ""

    @property
    def ident(self) -> str:
        """Stable identity waivers match against (line numbers excluded
        — they drift under unrelated edits)."""
        return f"{self.rule}:{self.path}:{self.message}"

    def __str__(self):
        loc = f"{self.path}:{self.line}: " if self.path else ""
        tag = " [WAIVED]" if self.waived else ""
        return f"[{self.rule}] {loc}{self.message}{tag}"


def rule(name: str):
    def deco(fn):
        RULES[name] = fn
        fn.rule_name = name
        return fn
    return deco


def run_rule(name: str) -> list:
    try:
        fn = RULES[name]
    except KeyError:
        raise ValueError(
            f"unknown rule {name!r}; registered: {sorted(RULES)}"
        ) from None
    return fn()


# ---------------------------------------------------------------------------
# Waivers
# ---------------------------------------------------------------------------


def load_waivers(path: str = None) -> list:
    """[{"rule", "match", "reason"}, ...] from the waiver file (missing
    file = no waivers; a malformed file raises — a torn waiver file
    must not silently un-waive the tree)."""
    path = path or WAIVERS_PATH
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    waivers = data["waivers"]
    for w in waivers:
        for k in ("rule", "match", "reason"):
            if not w.get(k):
                raise ValueError(
                    f"waiver {w} missing required field {k!r} — every "
                    f"waiver needs a rule, a match, and a justification")
    return waivers


def apply_waivers(violations: list, waivers: list) -> tuple:
    """Mark waived violations; returns (unwaived, waived,
    stale_waivers) — a stale waiver matches nothing and should be
    deleted."""
    used = [False] * len(waivers)
    for v in violations:
        for i, w in enumerate(waivers):
            if w["rule"] == v.rule and w["match"] in v.ident:
                v.waived = True
                v.waiver_reason = w["reason"]
                used[i] = True
                break
    unwaived = [v for v in violations if not v.waived]
    waived = [v for v in violations if v.waived]
    stale = [w for w, u in zip(waivers, used) if not u]
    return unwaived, waived, stale


def run_rules(names=None, waivers_path: str = None) -> dict:
    """Run rules and fold in waivers; the dict is the JSON-report shape
    ``scripts/lint_dist.py`` emits and ``bench.py`` stamps."""
    names = sorted(RULES) if names is None else list(names)
    violations: list = []
    for name in names:
        violations += run_rule(name)
    unwaived, waived, stale = apply_waivers(
        violations, load_waivers(waivers_path))
    return {
        "rules_run": names,
        "violations": [str(v) for v in unwaived],
        "waived": [{"violation": str(v), "reason": v.waiver_reason}
                   for v in waived],
        "stale_waivers": stale,
        "ok": not unwaived,
    }


# ---------------------------------------------------------------------------
# Shared source scanning
# ---------------------------------------------------------------------------


def _py_files(*roots):
    for root in roots:
        for dirpath, _, names in os.walk(os.path.join(REPO, root)):
            for name in sorted(names):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def _rel(path):
    return os.path.relpath(path, REPO)


# ---------------------------------------------------------------------------
# Rule: kernel-entry-annotated (from tests/test_observability.py)
# ---------------------------------------------------------------------------

#: Public entry points without a ``ctx: *Context`` parameter that must
#: still be annotated (the discovery heuristic below cannot see them).
ANNOTATE_REQUIRED_ENTRIES = {
    ("flash_attention.py", "flash_attention"),
    ("group_gemm.py", "group_gemm"),
    ("flash_decode.py", "sp_gqa_decode"),
}

#: Floor on the discovered entry-point surface: fewer means the
#: discovery heuristic broke, not that the library shrank.
ANNOTATE_MIN_ENTRIES = 14


def kernel_module_functions():
    """[(module file, FunctionDef node, source segment)] for every
    top-level function in triton_dist_tpu/kernels."""
    out = []
    for path in sorted(glob.glob(os.path.join(_KERNELS_DIR, "*.py"))):
        src = open(path).read()
        for node in ast.parse(src).body:
            if isinstance(node, ast.FunctionDef):
                out.append((os.path.basename(path), node,
                            ast.get_source_segment(src, node) or ""))
    return out


@rule("kernel-entry-annotated")
def check_kernel_entries_annotated() -> list:
    """Every public host-level kernel entry (any top-level
    non-underscore function taking ``ctx: <...>Context``, plus
    :data:`ANNOTATE_REQUIRED_ENTRIES`) must contain ``with annotate(``
    or (transitively) call a function that does — the launch-metadata
    contract the reference keeps via its proton hooks
    (allgather_gemm.py:120-130)."""
    funcs = kernel_module_functions()
    entries = set(ANNOTATE_REQUIRED_ENTRIES)
    for fname, node, seg in funcs:
        if node.name.startswith("_"):
            continue
        for a in node.args.args + node.args.kwonlyargs:
            if a.arg == "ctx" and a.annotation is not None and \
                    "Context" in ast.unparse(a.annotation):
                entries.add((fname, node.name))
    out = []
    if len(entries) < ANNOTATE_MIN_ENTRIES:
        out.append(Violation(
            "kernel-entry-annotated",
            f"entry-point discovery found only {len(entries)} entries "
            f"(expected >= {ANNOTATE_MIN_ENTRIES}) — the ctx-parameter "
            f"heuristic or the required-entries list broke",
            path="triton_dist_tpu/kernels"))
    covered = {node.name for _, node, seg in funcs
               if "with annotate(" in seg}
    if not covered:
        out.append(Violation(
            "kernel-entry-annotated",
            "no annotated kernel entries found at all",
            path="triton_dist_tpu/kernels"))
        return out
    for _ in range(8):   # transitive delegation (autotuned -> tunable
        grew = False     # -> entry is 2 hops)
        for _, node, seg in funcs:
            if node.name in covered:
                continue
            if any(re.search(rf"\b{re.escape(c)}\(", seg)
                   for c in covered):
                covered.add(node.name)
                grew = True
        if not grew:
            break
    for fname, name in sorted(entries):
        if name not in covered:
            out.append(Violation(
                "kernel-entry-annotated",
                f"public kernel entry point {name}() has no "
                f"profiling.annotate launch-metadata span (direct or "
                f"delegated) — add `with annotate(name, flops=, "
                f"bytes_accessed=)` around the dispatch (see "
                f"ag_gemm_gathered)",
                path=f"triton_dist_tpu/kernels/{fname}"))
    return out


# ---------------------------------------------------------------------------
# Rules: trace taxonomy (from tests/test_serve_trace.py)
# ---------------------------------------------------------------------------


@rule("finish-reasons-registered")
def check_finish_reasons_registered() -> list:
    """Every ``FinishReason`` retires through a registered ``retire``
    event — a new retirement reason cannot silently skip the flight
    recorder."""
    from triton_dist_tpu.serve import FinishReason
    from triton_dist_tpu.serve import trace as trace_mod

    out = []
    for fr in FinishReason:
        if fr.value not in trace_mod.RETIRE_REASONS:
            out.append(Violation(
                "finish-reasons-registered",
                f"FinishReason.{fr.name} has no registered retire "
                f"event (add it to serve/trace.RETIRE_REASONS)",
                path="triton_dist_tpu/serve/trace.py"))
    if "retire" not in trace_mod.EVENT_TYPES:
        out.append(Violation(
            "finish-reasons-registered",
            "'retire' missing from serve/trace.EVENT_TYPES",
            path="triton_dist_tpu/serve/trace.py"))
    return out


@rule("fire-points-registered")
def check_fire_points_registered() -> list:
    """Every ``.fire("<point>"`` seam in the source tree maps to a
    registered fault event type — an injection point added without
    registration fails lint (and tier-1) instead of silently skipping
    the recorder."""
    from triton_dist_tpu.serve import trace as trace_mod

    points: dict = {}
    for path in _py_files("triton_dist_tpu"):
        with open(path, encoding="utf-8") as f:
            for m in re.finditer(r'\.fire\(\s*"(\w+)"', f.read()):
                points.setdefault(m.group(1), _rel(path))
    out = []
    if not points:
        out.append(Violation(
            "fire-points-registered",
            "no .fire() seams found at all — expected at least the "
            "PR 3 injection points (the grep broke)",
            path="triton_dist_tpu"))
    for point, path in sorted(points.items()):
        if point not in trace_mod.FAULT_POINT_EVENTS:
            out.append(Violation(
                "fire-points-registered",
                f"fault point '{point}' has no registered event type "
                f"(add it to serve/trace.FAULT_POINT_EVENTS)",
                path=path))
    for point, ev in sorted(trace_mod.FAULT_POINT_EVENTS.items()):
        if ev not in trace_mod.EVENT_TYPES:
            out.append(Violation(
                "fire-points-registered",
                f"FAULT_POINT_EVENTS['{point}'] = '{ev}' is not a "
                f"registered EVENT_TYPE",
                path="triton_dist_tpu/serve/trace.py"))
    return out


# ---------------------------------------------------------------------------
# Rule: no-unseeded-randomness
# ---------------------------------------------------------------------------

#: module-level numpy draws / unseeded constructors that make a run
#: unreproducible; seeded forms (``default_rng(seed)``,
#: ``Random(seed)``, ``np.random.seed`` in scripts) stay legal.
_RANDOM_PATTERNS = (
    # np.random.<draw>( — everything except the seeded constructor
    (re.compile(r"\bnp\.random\.(?!default_rng\b|seed\b|Generator\b)"
                r"(\w+)\s*\("),
     "module-level np.random.{0}() draws from hidden global state"),
    (re.compile(r"\bnp\.random\.default_rng\(\s*\)"),
     "np.random.default_rng() with no seed is entropy-seeded"),
    (re.compile(r"\brandom\.Random\(\s*\)"),
     "random.Random() with no seed is entropy-seeded"),
    (re.compile(r"(?<![\w.])random\.(random|randint|choice|shuffle|"
                r"uniform|randrange|sample|gauss)\s*\("),
     "stdlib random.{0}() draws from the global unseeded RNG"),
)


@rule("no-unseeded-randomness")
def check_no_unseeded_randomness() -> list:
    """Library and script code must not draw from unseeded RNGs: every
    chaos schedule, sampler, and jitter must replay bit-identically
    from its recorded seed (the whole deterministic-chaos story —
    runtime/faults.py — rests on this).  Take a key/seed parameter
    instead; justified exceptions go in LINT_WAIVERS.json."""
    out = []
    self_path = os.path.abspath(__file__)
    for path in _py_files("triton_dist_tpu", "scripts"):
        if os.path.abspath(path) == self_path:
            continue   # the pattern/message table above matches itself
        with open(path, encoding="utf-8") as f:
            for ln, text in enumerate(f, 1):
                stripped = text.split("#", 1)[0]
                for pat, msg in _RANDOM_PATTERNS:
                    m = pat.search(stripped)
                    if m:
                        arg = m.group(1) if m.groups() else ""
                        out.append(Violation(
                            "no-unseeded-randomness",
                            msg.format(arg), path=_rel(path), line=ln))
    return out


# ---------------------------------------------------------------------------
# Rule: shed-paths-observable
# ---------------------------------------------------------------------------

#: Serving-policy modules whose degrade decisions the rule audits (the
#: scheduler is a mechanism layer — its pickers mutate no counters; the
#: caller that acts on the pick is the accountable path).
_SHED_POLICY_MODULES = ("serve/engine.py", "serve/fleet.py",
                       "serve/disagg.py", "serve/net.py")

#: Function names that constitute a shed/downgrade/preempt decision
#: (anchored to name-segment starts: "unfinished"/"pushed" are not
#: sheds).
_SHED_NAME_PAT = re.compile(
    r"(?:^|_)(?:shed|preempt|expire|brownout|degrade)")

#: Evidence the path counts (metrics) and explains itself (trace/audit).
_SHED_METRICS_PAT = re.compile(
    r"self\.metrics\b|\b_carry\.|\bobserve_\w+\(|ingress_shed_by_class")
_SHED_TRACE_PAT = re.compile(r"\.emit\(|\baudit\.record\(")

#: Fewer matching decision paths than this means the name heuristic
#: broke (renames), not that overload handling disappeared.
_SHED_MIN_PATHS = 4


@rule("shed-paths-observable")
def check_shed_paths_observable() -> list:
    """Every shed/downgrade/preempt decision path in the serving policy
    layers must increment a metrics counter AND land a trace/audit
    event — a degrade decision that is invisible to both the scrape and
    the flight recorder is un-debuggable precisely when it matters
    (overload).  A path may instead delegate to another function that
    carries both markers itself (e.g. ``_expire`` retiring through
    ``_retire``); justified exceptions go in LINT_WAIVERS.json."""
    fns: list = []  # (relpath, node, segment)
    for relmod in _SHED_POLICY_MODULES:
        path = os.path.join(REPO, "triton_dist_tpu", relmod)
        src = open(path, encoding="utf-8").read()
        for node in ast.walk(ast.parse(src)):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.append((_rel(path), node,
                            ast.get_source_segment(src, node) or ""))
    # functions that carry both markers themselves are valid delegation
    # targets: calling one makes the caller's decision observable
    observable = {node.name for _, node, seg in fns
                  if _SHED_METRICS_PAT.search(seg)
                  and _SHED_TRACE_PAT.search(seg)}
    out = []
    checked = 0
    for relpath, node, seg in fns:
        if not _SHED_NAME_PAT.search(node.name):
            continue
        checked += 1
        delegates = any(re.search(rf"\b{re.escape(t)}\(", seg)
                        for t in observable if t != node.name)
        has_metrics = bool(_SHED_METRICS_PAT.search(seg)) or delegates
        has_trace = bool(_SHED_TRACE_PAT.search(seg)) or delegates
        if not (has_metrics and has_trace):
            missing = [w for w, ok in (("a metrics increment",
                                        has_metrics),
                                       ("a trace/audit event",
                                        has_trace)) if not ok]
            out.append(Violation(
                "shed-paths-observable",
                f"{node.name}() sheds/degrades without "
                f"{' or '.join(missing)} (and delegates to no "
                f"observable path) — overload decisions must never "
                f"be silent",
                path=relpath, line=node.lineno))
    if checked < _SHED_MIN_PATHS:
        out.append(Violation(
            "shed-paths-observable",
            f"only {checked} shed/preempt/expire/brownout paths found "
            f"(expected >= {_SHED_MIN_PATHS}) — the name heuristic "
            f"broke, update _SHED_NAME_PAT",
            path="triton_dist_tpu/serve"))
    return out


# ---------------------------------------------------------------------------
# Rule: collective-ids-unique
# ---------------------------------------------------------------------------


@rule("collective-ids-unique")
def check_collective_ids_unique() -> list:
    """Every ``collective_id`` in kernels/collective_ids.py must be
    distinct: two collective kernels sharing a barrier-semaphore id can
    cross-satisfy each other's entry barriers on hardware."""
    path = os.path.join(_KERNELS_DIR, "collective_ids.py")
    ids: dict = {}
    out = []
    tree = ast.parse(open(path).read())
    for node in tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, int):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    ids.setdefault(node.value.value, []).append(t.id)
    if not ids:
        out.append(Violation(
            "collective-ids-unique",
            "no integer collective ids found (the parse broke)",
            path=_rel(path)))
    for value, names in sorted(ids.items()):
        if len(names) > 1:
            out.append(Violation(
                "collective-ids-unique",
                f"collective_id {value} assigned to {sorted(names)} — "
                f"ids must be pairwise distinct",
                path=_rel(path)))
    return out


# ---------------------------------------------------------------------------
# Rule: ring-schedules-clean (the CommSchedule checker as a lint rule)
# ---------------------------------------------------------------------------

#: World sizes the lint rule sweeps — 2 (the degenerate ring), a run of
#: non-pow2 sizes (the slot maps' hard cases), and pow2 up to 32.
SCHEDULE_WORLDS = (2, 3, 4, 5, 6, 7, 8, 12, 16, 32)


@rule("ring-schedules-clean")
def check_ring_schedules() -> list:
    """Every registered kernel CommSchedule must simulate clean (no
    deadlock, no stranded credit, happens-before on every remote read,
    write-once outputs, bijective slot maps) at every world size in
    :data:`SCHEDULE_WORLDS`."""
    from triton_dist_tpu.analysis.comm_schedule import (
        SCHEDULE_BUILDERS,
        build_schedule,
    )
    from triton_dist_tpu.analysis.schedule_check import check_schedule

    out = []
    for kernel in sorted(SCHEDULE_BUILDERS):
        for world in SCHEDULE_WORLDS:
            for v in check_schedule(build_schedule(kernel, world)):
                out.append(Violation(
                    "ring-schedules-clean",
                    f"{kernel} world={world}: {v}",
                    path="triton_dist_tpu/analysis/comm_schedule.py"))
    return out


# ---------------------------------------------------------------------------
# Rule: durable-writes-integrity
# ---------------------------------------------------------------------------

#: A write-mode open or a json.dump in the serving layer — the
#: candidate durable-artifact producers the rule audits.
_DW_WRITE_PAT = re.compile(
    r"json\.dump\(|open\([^)\n]*[\"']wt?[\"']")

#: Atomicity evidence: the function publishes via rename (or delegates
#: to the shared helper, which does).
_DW_ATOMIC_PAT = re.compile(r"os\.replace\(|atomic_write_json\(")

#: Digest evidence: the written bytes carry a verifiable CRC stamp.
_DW_DIGEST_PAT = re.compile(
    r"atomic_write_json\(|stamp_crc\(|canonical_crc\(|crc32")

#: Fewer audited write sites than this means the detection pattern
#: broke (refactor moved the writers), not that serving stopped
#: persisting state — the shed-paths-observable self-blindness guard.
_DW_MIN_SITES = 4


@rule("durable-writes-integrity")
def check_durable_writes_integrity() -> list:
    """Every ``json.dump`` / ``open(..., "w")`` write of a durable
    serving artifact under ``serve/`` must route through the shared
    atomic-write + digest helper (``integrity.atomic_write_json``) or
    carry equivalent evidence itself — rename-publish atomicity AND a
    CRC stamp on the bytes (the journal's framing methods).  A durable
    artifact written raw is exactly the silent-corruption surface
    ISSUE 20 closed; justified exceptions (ephemeral discovery files,
    external-tool export formats) go in LINT_WAIVERS.json."""
    out = []
    checked = 0
    serve_dir = os.path.join(REPO, "triton_dist_tpu", "serve")
    for path in sorted(glob.glob(os.path.join(serve_dir, "*.py"))):
        if os.path.basename(path) == "integrity.py":
            continue   # the helper's own implementation
        src = open(path, encoding="utf-8").read()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            seg = ast.get_source_segment(src, node) or ""
            if not _DW_WRITE_PAT.search(seg):
                continue
            checked += 1
            has_atomic = bool(_DW_ATOMIC_PAT.search(seg))
            has_digest = bool(_DW_DIGEST_PAT.search(seg))
            if not (has_atomic and has_digest):
                missing = [w for w, ok in (
                    ("rename-publish atomicity", has_atomic),
                    ("a CRC digest stamp", has_digest)) if not ok]
                out.append(Violation(
                    "durable-writes-integrity",
                    f"{node.name}() writes a durable artifact without "
                    f"{' or '.join(missing)} — route it through "
                    f"integrity.atomic_write_json",
                    path=_rel(path), line=node.lineno))
    if checked < _DW_MIN_SITES:
        out.append(Violation(
            "durable-writes-integrity",
            f"only {checked} durable write sites found under serve/ "
            f"(expected >= {_DW_MIN_SITES}) — the detection pattern "
            f"broke, update _DW_WRITE_PAT",
            path="triton_dist_tpu/serve"))
    return out
