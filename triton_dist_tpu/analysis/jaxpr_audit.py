"""Jaxpr auditing of the serving engine's compiled device programs.

The serving stack's two worst historical bug classes were both
*trace-level* properties nobody checked mechanically: the
executable-cache fork (PRs 7/12 — one program tracing under two
argument placements, found by hand signature-diffing) and misplaced
collective/donation seams.  Every engine device program is registered
behind a ``jit_cache.CountingJit`` (world-1) or ``serve.mesh.
ShardedProgram`` (mesh) wrapper that captures the abstract signature of
each distinct traced call — so this module can re-trace EVERY program
the engine actually compiled (``jax.make_jaxpr`` over the captured
``ShapeDtypeStruct`` signatures, device-free) and audit the jaxpr:

- **no host callbacks in fused hot paths** — a ``pure_callback`` /
  ``io_callback`` / ``debug_callback`` inside a decode/prefill program
  re-serializes the device loop on the host (the dispatch economics the
  horizon exists to remove);
- **donated buffers actually consumed** — each ``donated_invars`` entry
  of a pjit must be used by the traced computation AND have a
  shape/dtype-matching output XLA can alias it to; an unusable donation
  silently doubles the KV pools' memory footprint;
- **collectives only at declared seams** — the per-program allowed
  collective set (``serve.mesh.collective_seams``: psum at the
  out-proj/FFN row-parallel seams and the sharded-vocab logits seam for
  ``kv_shard="heads"``, the SP combine's gather for ``"seq"``, nothing
  anywhere else; world-1 programs allow none);
- **statics drawn from declared ladders** — every captured static kwarg
  (the horizon's ``H``, the spec round's ``K``) must sit on its
  declared ladder; an off-ladder static is exactly the retrace-hazard /
  cache-fork class warmup's fixed point exists to prevent.

Entry points: :func:`audit_program` for one registry record,
:func:`audit_engine` for a whole ``ServeEngine``
(``engine.program_registry()``).
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Optional

import jax

#: Wire/collective primitives (jax 0.4.x names; ``psum2`` is psum's
#: shard_map spelling).  ``pbroadcast`` is NOT here: it is shard_map's
#: type-level replication adjustment, no bytes move.
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "ppermute", "pgather", "all_gather",
    "all_gather_invariant", "all_to_all", "reduce_scatter",
    "psum_scatter",
})

#: Host-callback primitives — never legal inside a fused hot path.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call",
})

#: jax spells some collectives differently across entry points /
#: versions; seams declare the canonical name.
_PRIM_CANON = {
    "psum2": "psum",
    "all_gather_invariant": "all_gather",
}


@dataclasses.dataclass
class AuditFinding:
    program: str
    #: "callback" | "donation" | "collective" | "ladder" — plus the
    #: meta outcomes "untraced" (registered but never called) and
    #: "retrace-failed" (captured signature would not re-trace)
    check: str
    message: str

    def __str__(self):
        return f"[{self.check}] {self.program}: {self.message}"


# ---------------------------------------------------------------------------
# Jaxpr walking
# ---------------------------------------------------------------------------


def _iter_subjaxprs(params: dict):
    for v in params.values():
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, jax.core.ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, jax.core.Jaxpr):
                    yield x


def jaxpr_stats(jaxpr) -> dict:
    """Recursive walk: primitive counts + per-pjit donation records.

    Returns ``{"prims": Counter, "donations": [(name, jaxpr,
    donated_invars)]}`` — donations carry the pjit's inner jaxpr so
    :func:`_check_donation` can test use + aliasability."""
    prims: Counter = Counter()
    donations: list = []

    def walk(j):
        for eqn in j.eqns:
            prims[eqn.primitive.name] += 1
            if eqn.primitive.name == "pjit":
                donated = eqn.params.get("donated_invars", ())
                if any(donated):
                    donations.append(
                        (eqn.params.get("name", "pjit"),
                         eqn.params["jaxpr"].jaxpr, tuple(donated)))
            for sub in _iter_subjaxprs(eqn.params):
                walk(sub)

    walk(jaxpr)
    return {"prims": prims, "donations": donations}


def _used_vars(jaxpr) -> set:
    used = set()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                used.add(v)
    for v in jaxpr.outvars:
        if not isinstance(v, jax.core.Literal):
            used.add(v)
    return used


def _check_donation(program: str, name: str, jaxpr, donated) -> list:
    """Donated pjit invars must be consumed: used by the computation and
    coverable by a shape/dtype-matching output (XLA aliases donated
    buffers only onto identical avals — an unmatched donation is a
    silent no-op that keeps both buffers live)."""
    findings = []
    used = _used_vars(jaxpr)
    out_avals = Counter()
    for v in jaxpr.outvars:
        if hasattr(v, "aval"):
            a = v.aval
            out_avals[(tuple(a.shape), str(a.dtype))] += 1
    for i, (v, d) in enumerate(zip(jaxpr.invars, donated)):
        if not d:
            continue
        a = v.aval
        key = (tuple(a.shape), str(a.dtype))
        if v not in used:
            findings.append(AuditFinding(
                program, "donation",
                f"{name}: donated argument {i} "
                f"({key[1]}{list(key[0])}) is never used by the traced "
                f"computation — the donation frees nothing"))
        elif out_avals[key] <= 0:
            findings.append(AuditFinding(
                program, "donation",
                f"{name}: donated argument {i} "
                f"({key[1]}{list(key[0])}) has no shape/dtype-matching "
                f"output to alias — XLA keeps both buffers live"))
        else:
            out_avals[key] -= 1
    return findings


# ---------------------------------------------------------------------------
# Program tracing (CountingJit / ShardedProgram signatures)
# ---------------------------------------------------------------------------


def _signatures(fn) -> list:
    """Captured (args_abs, kwargs_abs) pairs of ``fn`` — a
    ``CountingJit`` (possibly wrapping a ``ShardedProgram``) or a bare
    ``ShardedProgram``."""
    inner = getattr(fn, "fn", fn)           # unwrap CountingJit
    if hasattr(inner, "_prog") and hasattr(inner, "captured"):
        # ShardedProgram: statics-key -> (placed_args_abs, statics)
        return [(args, kw) for (args, kw) in inner.captured.values()]
    cap = getattr(fn, "captured", None)
    if cap:
        return list(cap.values())
    return []


def _trace(fn, args_abs, kwargs):
    inner = getattr(fn, "fn", fn)
    if hasattr(inner, "_prog"):
        prog = inner._prog(tuple(sorted(kwargs.items())))
        return jax.make_jaxpr(prog)(*args_abs)
    # make_jaxpr turns EVERY argument it receives into a tracer — but
    # static kwargs (the horizon's H, prefill's n_valid) were concrete
    # Python values at the real call and must stay concrete here, or
    # the inner jit hashes a tracer as a static / branches on one.
    # Array-shaped kwargs (ShapeDtypeStructs) trace; the rest closes
    # over concretely.
    traced_kw = {k: v for k, v in kwargs.items()
                 if isinstance(v, jax.ShapeDtypeStruct)}
    static_kw = {k: v for k, v in kwargs.items()
                 if not isinstance(v, jax.ShapeDtypeStruct)}

    def call(*args, **tkw):
        return inner(*args, **tkw, **static_kw)

    return jax.make_jaxpr(call)(*args_abs, **traced_kw)


def audit_program(rec: dict) -> list:
    """Audit one registry record ``{"name", "fn", "ladders", "seams"}``.

    ``ladders`` maps static kwarg name -> allowed values; ``seams`` maps
    collective primitive name -> expected occurrence count per trace
    (``None`` = any count > 0 allowed).  Collective primitives absent
    from ``seams`` are violations wherever they appear.  Returns
    [] when every captured signature audits clean; records with no
    captured signatures return a single "untraced" finding so a
    registry entry cannot silently fall out of coverage (callers that
    know a program is legitimately idle filter these).
    """
    name = rec["name"]
    fn = rec["fn"]
    ladders = rec.get("ladders") or {}
    seams = rec.get("seams") or {}
    sigs = _signatures(fn)
    if not sigs:
        return [AuditFinding(
            name, "untraced",
            "no captured trace signature — program never called, so "
            "nothing was audited")]
    findings: list = []
    seen: set = set()
    for args_abs, kwargs in sigs:
        # ladder membership of every captured static
        for k, allowed in ladders.items():
            if k in kwargs and kwargs[k] not in allowed:
                f = AuditFinding(
                    name, "ladder",
                    f"static {k}={kwargs[k]!r} is off the declared "
                    f"ladder {list(allowed)} — every off-ladder static "
                    f"is one more compiled executable (the cache-fork "
                    f"class)")
                if str(f) not in seen:
                    seen.add(str(f))
                    findings.append(f)
        try:
            closed = _trace(fn, args_abs, kwargs)
        except Exception as e:  # noqa: BLE001 — surface, don't crash
            f = AuditFinding(name, "retrace-failed",
                             f"re-trace failed: {type(e).__name__}: {e}")
            if str(f) not in seen:
                seen.add(str(f))
                findings.append(f)
            continue
        stats = jaxpr_stats(closed.jaxpr)
        canon: Counter = Counter()
        for prim, n in stats["prims"].items():
            canon[_PRIM_CANON.get(prim, prim)] += n
        for prim, n in sorted(canon.items()):
            if prim in CALLBACK_PRIMS:
                f = AuditFinding(
                    name, "callback",
                    f"host callback primitive '{prim}' x{n} inside a "
                    f"fused hot-path program")
                if str(f) not in seen:
                    seen.add(str(f))
                    findings.append(f)
            if prim in COLLECTIVE_PRIMS:
                if prim not in seams:
                    f = AuditFinding(
                        name, "collective",
                        f"collective '{prim}' x{n} outside the declared "
                        f"seams {sorted(seams) or '{}'}")
                elif seams[prim] is not None and n != seams[prim]:
                    f = AuditFinding(
                        name, "collective",
                        f"collective '{prim}' appears x{n}, declared "
                        f"seam count is {seams[prim]}")
                else:
                    continue
                if str(f) not in seen:
                    seen.add(str(f))
                    findings.append(f)
        for pjit_name, inner_jaxpr, donated in stats["donations"]:
            for f in _check_donation(name, pjit_name, inner_jaxpr,
                                     donated):
                if str(f) not in seen:
                    seen.add(str(f))
                    findings.append(f)
    return findings


def audit_engine(engine, *, include_untraced: bool = False) -> dict:
    """Audit every program in ``engine.program_registry()``.

    Returns ``{"programs": [name...], "audited": [name...],
    "skipped": [name...], "findings": [AuditFinding...]}`` — skipped =
    registered but never traced (legitimate for paths the engine's
    traffic never exercised, e.g. the verify program on a spec-less
    engine); pass ``include_untraced=True`` to turn those into
    findings instead."""
    report = {"programs": [], "audited": [], "skipped": [],
              "findings": []}
    for rec in engine.program_registry():
        report["programs"].append(rec["name"])
        findings = audit_program(rec)
        if len(findings) == 1 and findings[0].check == "untraced":
            report["skipped"].append(rec["name"])
            if include_untraced:
                report["findings"] += findings
            continue
        report["audited"].append(rec["name"])
        report["findings"] += findings
    return report
