"""Static analysis for the distributed kernel library and the serving
stack — runnable device-free on CPU (docs/analysis.md).

Three passes over three layers:

- :mod:`comm_schedule` + :mod:`schedule_check` — a small ``CommSchedule``
  IR (steps x ranks -> sends/recvs/signals/waits/tiles-written) populated
  by one builder per overlapped kernel, and a symbolic vector-clock
  simulator that proves, for every world size 2-32, signal/wait credit
  balance (no deadlock, no stranded credit), happens-before on every
  remote read against its producing write, write-once output tiles, and
  per-step slot-map bijectivity.  A seeded mutation self-test (dropped
  signal, swapped slot, doubled wait, double-written tile) keeps the
  checker honest: every corruption class must be caught.
- :mod:`jaxpr_audit` — traces every registered engine device program
  (the ``CountingJit``/``ShardedProgram`` registry) and checks no host
  callbacks in fused hot paths, donated buffers actually consumed,
  collectives only at declared seams, and statics drawn from declared
  ladders (the retrace-hazard / executable-cache-fork class).
- :mod:`rules` — the source-lint rule registry (the grep meta-tests,
  promoted): annotation coverage, trace-taxonomy closure, no unseeded
  randomness, unique collective ids, plus the schedule checker as a
  rule.  ``scripts/lint_dist.py`` is the CLI driver (JSON report,
  waiver file, nonzero exit on unwaived violation).
"""

from triton_dist_tpu.analysis.comm_schedule import (  # noqa: F401
    SCHEDULE_BUILDERS,
    CommSchedule,
    Op,
    arrival_slots,
    build_schedule,
)
from triton_dist_tpu.analysis.schedule_check import (  # noqa: F401
    MUTATIONS,
    check_schedule,
    mutate,
    mutation_self_test,
)
from triton_dist_tpu.analysis.jaxpr_audit import (  # noqa: F401
    audit_engine,
    audit_program,
)
from triton_dist_tpu.analysis.rules import (  # noqa: F401
    RULES,
    Violation,
    load_waivers,
    run_rule,
    run_rules,
)
