"""CommSchedule — the IR the ring-schedule checker runs on.

Every overlapped kernel in ``triton_dist_tpu/kernels/`` is, stripped of
its MXU work, a communication schedule: per ring step and per rank, an
ordered list of remote DMAs (send + arrival signal fused, the TPU
semantics of ``dl.remote_copy``), semaphore signals/waits (``dl.notify``
/ ``dl.wait``, credit backpressure), and buffer tile reads/writes.  The
real kernels encode that schedule implicitly in Pallas control flow where
an off-by-one deadlocks or silently reads a stale tile on hardware that
CPU tier-1 can never exercise.  This module makes the schedule an
explicit, checkable artifact: one ``build_*`` function per kernel emits
the kernel's exact op sequence for a given world size, mirroring the
kernel source line-for-line (each builder's docstring cites the lines it
transcribes), and :mod:`schedule_check` symbolically executes it.

The IR deliberately models TPU semantics, not NVSHMEM's:

- a ``send`` is ``pltpu.make_async_remote_copy``: the arrival increment
  on the receiver's ``rsem`` is part of the same transaction as the data
  (no separate flag-store + fence), and the sender's ``ssem`` counts
  completion of the source read (drain before source reuse);
- a ``wait`` is ``pltpu.semaphore_wait`` — a full acquire barrier for
  DMA'd data (no ``consume_token``);
- local async copies are sends to self (one completion semaphore).

Payload identity rides every send/write as a ``label`` tuple (e.g.
``("seg", j)`` — A-segment j of the allgather ring), and every read
declares the label it must observe — so the checker proves not just
"some bytes arrived" but "the bytes the schedule owes this step arrived"
(a swapped landing slot is a label mismatch, not a silent wrong answer).

Slot maps: builders whose consumption order is slot-addressed publish
``slot_maps[step] = [slot consumed by rank r at this step]`` — for the
AG ring that is kprobe's arrival-order decomposition
``slots[r] = (r - s) % world`` (:func:`arrival_slots`, shared with
``runtime/kprobe.py``'s phase-sliced replay) — and the checker asserts
each step's map is a bijection on ranks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

#: Builders registered by :func:`schedule_builder`; name -> fn(world).
SCHEDULE_BUILDERS: dict[str, Callable] = {}


def arrival_slots(step: int, world: int) -> list[int]:
    """The AG ring's arrival-order slot map: at ring step ``s`` rank
    ``r`` consumes segment slot ``(r - s) % world`` (step 0 is always
    the local segment — the reference's rank swizzle for free).  Shared
    contract with ``runtime/kprobe.py``'s phase-sliced replay, which
    stamps the same map into its per-step report slices."""
    return [(r - step) % world for r in range(world)]


@dataclasses.dataclass
class Op:
    """One schedule event on one rank (program order within the rank).

    kind:
      ``send``    async (remote or to-self) DMA: reads ``(src_buf,
                  src_slot)`` (must hold ``label``), writes ``(buf,
                  slot)`` on rank ``dst``, increments ``rsem`` there and
                  ``ssem`` here on completion.  ``final`` marks the
                  landing write as an output-tile completion.
      ``wait``    ``semaphore_wait(sem, count)`` — blocks.
      ``signal``  ``semaphore_signal(sem, inc=count)`` on rank ``dst``.
      ``write``   local tile write of ``label`` into ``(buf, slot)``;
                  ``final`` = this is the tile's completing write.
      ``read``    local read of ``(buf, slot)``; must observe ``label``
                  (``None`` = any fully-ordered data).
    """

    kind: str
    step: int = -1                 # ring step (-1 = pre/postlude)
    sem: str = ""                  # wait/signal
    count: int = 1
    dst: int = -1                  # send/signal target rank
    buf: str = ""
    slot: int = 0
    src_buf: str = ""
    src_slot: int = 0
    rsem: str = ""
    ssem: str = ""
    label: Optional[tuple] = None
    final: bool = False
    note: str = ""


@dataclasses.dataclass
class CommSchedule:
    """The whole kernel schedule at one world size."""

    kernel: str
    world: int
    #: per-rank program-ordered op list
    ranks: list
    #: (rank, buf, slot, label): data resident before the kernel entry
    init: list = dataclasses.field(default_factory=list)
    #: buf -> slot count: every slot must receive EXACTLY one final
    #: write on every rank (the write-once output contract)
    outputs: dict = dataclasses.field(default_factory=dict)
    #: step -> per-rank consumed slot (bijectivity-checked when present)
    slot_maps: dict = dataclasses.field(default_factory=dict)
    meta: dict = dataclasses.field(default_factory=dict)

    def n_ops(self) -> int:
        return sum(len(r) for r in self.ranks)


def schedule_builder(name: str):
    def deco(fn):
        SCHEDULE_BUILDERS[name] = fn
        return fn
    return deco


def build_schedule(kernel: str, world: int) -> CommSchedule:
    """Build one kernel's schedule IR at ``world`` ranks (>= 2; the
    world-1 degenerate paths ship no comm and have nothing to check)."""
    if world < 2:
        raise ValueError(f"world must be >= 2, got {world}")
    try:
        fn = SCHEDULE_BUILDERS[kernel]
    except KeyError:
        raise ValueError(
            f"unknown kernel {kernel!r}; registered: "
            f"{sorted(SCHEDULE_BUILDERS)}") from None
    return fn(world)


# ---------------------------------------------------------------------------
# Shared preludes
# ---------------------------------------------------------------------------


def _neighbor_barrier(ops: list, me: int, world: int) -> None:
    """The ring kernels' entry barrier (allgather_gemm.py:339-344,
    gemm_reduce_scatter.py:144-149, moe_reduce_rs.py:136-142): signal
    both ring neighbors' barrier semaphores, wait for 2."""
    ops.append(Op("signal", dst=(me - 1) % world, sem="barrier"))
    ops.append(Op("signal", dst=(me + 1) % world, sem="barrier"))
    ops.append(Op("wait", sem="barrier", count=2))


def _full_barrier(ops: list, me: int, world: int) -> None:
    """``dl.barrier_all`` (language/primitives.py:270-310): signal every
    peer, wait for world-1."""
    for i in range(1, world):
        ops.append(Op("signal", dst=(me + i) % world, sem="barrier"))
    ops.append(Op("wait", sem="barrier", count=world - 1))


# ---------------------------------------------------------------------------
# ag_gemm — overlapped AllGather-GEMM ring producer
# ---------------------------------------------------------------------------


@schedule_builder("ag_gemm")
def build_ag_gemm(world: int) -> CommSchedule:
    """``_ag_gemm_kernel`` (allgather_gemm.py:240-420, chunks=1): stage
    the local segment into the gathered buffer (waited at exit), barrier
    with ring neighbors, then per step ``s``: ring-forward the held
    segment — slot ``(me - s) % world`` — to the right neighbor, compute
    its GEMM tile, fold the NEXT segment's recv wait into the pipeline
    prefetch, and drain the forward's send before the next step.  No
    credit semaphore: every landing slot is globally unique (each
    segment visits each rank once), so slots are never reused."""
    sched = CommSchedule("ag_gemm", world, [[] for _ in range(world)])
    for me in range(world):
        sched.init.append((me, "a", 0, ("seg", me)))
    sched.outputs = {"out": world, "ag": world}
    for s in range(world):
        sched.slot_maps[s] = arrival_slots(s, world)

    for me in range(world):
        ops = sched.ranks[me]
        right = (me + 1) % world
        # Staging copy: a -> ag[me] (to-self DMA; waited at kernel exit).
        ops.append(Op("send", step=-1, dst=me, src_buf="a", src_slot=0,
                      buf="ag", slot=me, rsem="copy_sem",
                      label=("seg", me), final=True, note="stage local"))
        _neighbor_barrier(ops, me, world)
        for s in range(world):
            slot = (me - s) % world
            src_buf, src_slot = ("a", 0) if s == 0 else ("ag", slot)
            if s < world - 1:
                # Forward launches BEFORE the step's compute so the wire
                # rides under the whole cycle (allgather_gemm.py:360-380).
                ops.append(Op("send", step=s, dst=right, src_buf=src_buf,
                              src_slot=src_slot, buf="ag", slot=slot,
                              rsem="recv", ssem="send",
                              label=("seg", slot), final=True))
            ops.append(Op("read", step=s, buf=src_buf, slot=src_slot,
                          label=("seg", slot), note="segment GEMM"))
            ops.append(Op("write", step=s, buf="out", slot=slot,
                          label=("tile", slot), final=True))
            if s < world - 1:
                # Next segment's arrival, waited inside this cycle's
                # prefetch callback (allgather_gemm.py:382-397)...
                ops.append(Op("wait", step=s, sem="recv",
                              note="prefetch next segment"))
                # ...then this cycle's forward drains (:404-410).
                ops.append(Op("wait", step=s, sem="send", note="drain"))
        ops.append(Op("wait", step=world - 1, sem="copy_sem",
                      note="staging validity at exit"))
    return sched


# ---------------------------------------------------------------------------
# gemm_rs / moe_reduce_rs — ring reduce-scatter with credit backpressure
# ---------------------------------------------------------------------------


def _ring_rs(kernel: str, world: int) -> CommSchedule:
    """The shared GEMM-RS / MoE-RS ring (gemm_reduce_scatter.py:103-201,
    moe_reduce_rs.py:120-196 — byte-identical schedules; the MoE kernel
    swaps the inner GEMM for a grouped one).  Per step ``s``: compute
    the partial for chunk ``(me - 1 - s) % world`` (own chunk ``me`` at
    the last step) into send slot ``s % 2``, fold the partial arriving
    from the left (credit the freed landing slot back), and ship the
    accumulated partial rightward into landing slot ``(s + 1) % 2`` —
    with per-slot DMA semaphores (a shared one could let the OTHER
    slot's completion satisfy a drain) and a credit semaphore stopping
    anyone from DMA-ing into a slot its owner still reads."""
    sched = CommSchedule(kernel, world, [[] for _ in range(world)])
    for me in range(world):
        for c in range(world):
            sched.init.append((me, "a", c, ("a_chunk", me, c)))
    sched.outputs = {"out": 1}
    # chunk consumed per step: the RS ring's slot map (bijective like
    # the AG ring's — it is the same rotation, phase-shifted).
    for s in range(world - 1):
        sched.slot_maps[s] = [(r - 1 - s) % world for r in range(world)]
    sched.slot_maps[world - 1] = list(range(world))

    for me in range(world):
        ops = sched.ranks[me]
        right = (me + 1) % world
        left = (me - 1) % world
        _neighbor_barrier(ops, me, world)
        for s in range(world):
            p = s % 2
            last = s == world - 1
            chunk = me if last else (me - 1 - s) % world
            dbuf, dslot = ("out", 0) if last else ("send", p)
            if s >= 2:
                # send slot p was last DMA'd at step s-2; drain before
                # the GEMM overwrites it (per-slot semaphore).
                ops.append(Op("wait", step=s, sem=f"send_sem{p}",
                              note="reuse send slot"))
            ops.append(Op("read", step=s, buf="a", slot=chunk,
                          label=("a_chunk", me, chunk), note="chunk GEMM"))
            ops.append(Op("write", step=s, buf=dbuf, slot=dslot,
                          label=("partial", chunk, 1), note="own partial"))
            if s >= 1:
                ops.append(Op("wait", step=s, sem=f"recv_sem{p}",
                              note="partial arrival"))
                ops.append(Op("read", step=s, buf="recv", slot=p,
                              label=("partial", chunk, s),
                              note="fold arriving partial"))
                ops.append(Op("write", step=s, buf=dbuf, slot=dslot,
                              label=("partial", chunk, s + 1),
                              final=last, note="fold"))
                # Slot p is free for left's step-(s+1) send.
                ops.append(Op("signal", step=s, dst=left, sem="credit"))
            elif last:
                # world == 1 cannot happen here (builders need >= 2);
                # world == 2's last step still folds above.
                pass
            if not last:
                if s >= 2:
                    # Right's landing slot (s+1)%2 was consumed at its
                    # step s-1; collect the credit before overwriting.
                    ops.append(Op("wait", step=s, sem="credit"))
                depth = s + 1 if s >= 1 else 1
                ops.append(Op("send", step=s, dst=right, src_buf=dbuf,
                              src_slot=dslot, buf="recv",
                              slot=(s + 1) % 2,
                              rsem=f"recv_sem{(s + 1) % 2}",
                              ssem=f"send_sem{p}",
                              label=("partial", chunk, depth)))
        # Postlude (gemm_reduce_scatter.py:192-201): drain the final
        # send (issued at step world-2) and the unconsumed credits.
        pfin = (world - 2) % 2
        ops.append(Op("wait", step=world - 1, sem=f"send_sem{pfin}",
                      note="final send drain"))
        n_credit_waits = max(world - 3, 0)
        ops.append(Op("wait", step=world - 1, sem="credit",
                      count=(world - 1) - n_credit_waits,
                      note="drain unconsumed credits"))
    return sched


@schedule_builder("gemm_rs")
def build_gemm_rs(world: int) -> CommSchedule:
    return _ring_rs("gemm_rs", world)


@schedule_builder("moe_reduce_rs")
def build_moe_reduce_rs(world: int) -> CommSchedule:
    return _ring_rs("moe_reduce_rs", world)


# ---------------------------------------------------------------------------
# ring_attention — KV-block ring with double-buffered slots + credits
# ---------------------------------------------------------------------------


@schedule_builder("ring_attention")
def build_ring_attention(world: int) -> CommSchedule:
    """``_ring_attention_fused_kernel`` (ring_attention.py:410-496): KV
    blocks ring rightward through two slots.  Step ``s`` waits the k/v
    arrivals into slot ``s % 2`` (s > 0), forwards them to slot
    ``(s+1) % 2`` on the right (credit-gated from s >= 1: the slot was
    consumed at right's step s-1), computes on the block — origin rank
    ``(me - s) % world``, ring_attention.py:280-283 — drains both sends,
    and credits the left neighbor once slot ``s % 2`` is free
    (s < world-2: the last two steps never reuse it)."""
    sched = CommSchedule("ring_attention", world,
                         [[] for _ in range(world)])
    for me in range(world):
        sched.init.append((me, "k", 0, ("kv_k", me)))
        sched.init.append((me, "v", 0, ("kv_v", me)))
    sched.outputs = {"o": 1}
    for s in range(world):
        sched.slot_maps[s] = arrival_slots(s, world)

    for me in range(world):
        ops = sched.ranks[me]
        right = (me + 1) % world
        left = (me - 1) % world
        # Stage local KV into slot 0 (to-self DMAs + waits, :431-434).
        ops.append(Op("send", step=-1, dst=me, src_buf="k", src_slot=0,
                      buf="kring", slot=0, rsem="copy",
                      label=("kv_k", me), note="stage k"))
        ops.append(Op("send", step=-1, dst=me, src_buf="v", src_slot=0,
                      buf="vring", slot=0, rsem="copy",
                      label=("kv_v", me), note="stage v"))
        ops.append(Op("wait", step=-1, sem="copy", count=2))
        _full_barrier(ops, me, world)
        for s in range(world):
            cur, nxt = s % 2, (s + 1) % 2
            src = (me - s) % world
            if s > 0:
                ops.append(Op("wait", step=s, sem="recv", count=2,
                              note="k+v arrival"))
            if s < world - 1:
                if s >= 1:
                    ops.append(Op("wait", step=s, sem="credit",
                                  note="right freed slot nxt"))
                ops.append(Op("send", step=s, dst=right, src_buf="kring",
                              src_slot=cur, buf="kring", slot=nxt,
                              rsem="recv", ssem="send",
                              label=("kv_k", src)))
                ops.append(Op("send", step=s, dst=right, src_buf="vring",
                              src_slot=cur, buf="vring", slot=nxt,
                              rsem="recv", ssem="send",
                              label=("kv_v", src)))
            ops.append(Op("read", step=s, buf="kring", slot=cur,
                          label=("kv_k", src), note="block update"))
            ops.append(Op("read", step=s, buf="vring", slot=cur,
                          label=("kv_v", src), note="block update"))
            if s < world - 1:
                ops.append(Op("wait", step=s, sem="send", count=2,
                              note="drain forwards"))
            if s < world - 2:
                ops.append(Op("signal", step=s, dst=left, sem="credit"))
        ops.append(Op("write", step=world - 1, buf="o", slot=0,
                      label=("attn_out", me), final=True))
    return sched


# ---------------------------------------------------------------------------
# all_to_all — full-mesh push with split-count plane
# ---------------------------------------------------------------------------


def _a2a_round(ops: list, me: int, world: int, *, nblk: int, pfx: str,
               step: int, with_splits: bool) -> None:
    """One ``_all_to_all_kernel`` round (all_to_all.py:140-222) at full
    (= ``nblk`` blocks per peer) splits: local segment copied to self,
    ``barrier_all``, split rows pushed on their own semaphore pair,
    payload blocks pushed, outgoing drains, then incoming waits for
    exactly the advertised counts."""
    # Local segment: send[me] -> recv[me], never touches the wire.
    for b in range(nblk):
        ops.append(Op("send", step=step, dst=me, src_buf=f"{pfx}send",
                      src_slot=me * nblk + b, buf=f"{pfx}recv",
                      slot=me * nblk + b, rsem=f"{pfx}copy",
                      label=("tok", me, me, b), final=True,
                      note="local segment"))
    if with_splits:
        ops.append(Op("send", step=step, dst=me, src_buf=f"{pfx}splits",
                      src_slot=me, buf=f"{pfx}rsplits", slot=me,
                      rsem=f"{pfx}copy", label=("split", me, me),
                      final=True))
    ops.append(Op("wait", step=step, sem=f"{pfx}copy",
                  count=nblk + (1 if with_splits else 0)))
    _full_barrier(ops, me, world)
    if with_splits:
        # Split counts first, on their own semaphore pair (:162-168).
        for i in range(1, world):
            peer = (me + i) % world
            ops.append(Op("send", step=step, dst=peer,
                          src_buf=f"{pfx}splits", src_slot=peer,
                          buf=f"{pfx}rsplits", slot=me,
                          rsem=f"{pfx}srecv", ssem=f"{pfx}ssend",
                          label=("split", me, peer), final=True))
    # Payload blocks (:172-185).
    for i in range(1, world):
        peer = (me + i) % world
        for b in range(nblk):
            ops.append(Op("send", step=step, dst=peer,
                          src_buf=f"{pfx}send", src_slot=peer * nblk + b,
                          buf=f"{pfx}recv", slot=me * nblk + b,
                          rsem=f"{pfx}recv", ssem=f"{pfx}send",
                          label=("tok", me, peer, b), final=True))
    # Outgoing drains (:187-203), then incoming (:205-222).
    if with_splits:
        ops.append(Op("wait", step=step, sem=f"{pfx}ssend",
                      count=world - 1))
    ops.append(Op("wait", step=step, sem=f"{pfx}send",
                  count=(world - 1) * nblk))
    if with_splits:
        ops.append(Op("wait", step=step, sem=f"{pfx}srecv",
                      count=world - 1))
        for p in range(world):
            if p != me:
                ops.append(Op("read", step=step, buf=f"{pfx}rsplits",
                              slot=p, label=("split", p, me)))
    ops.append(Op("wait", step=step, sem=f"{pfx}recv",
                  count=(world - 1) * nblk))


def _a2a_read_all(ops: list, me: int, world: int, *, nblk: int,
                  pfx: str, step: int, note: str) -> None:
    for p in range(world):
        for b in range(nblk):
            ops.append(Op("read", step=step, buf=f"{pfx}recv",
                          slot=p * nblk + b, label=("tok", p, me, b),
                          note=note))


@schedule_builder("all_to_all")
def build_all_to_all(world: int, nblk: int = 2) -> CommSchedule:
    """``_all_to_all_kernel`` (all_to_all.py:140-222) at full splits
    (every peer segment = ``nblk`` blocks; partial splits only shrink
    the block counts both drain loops derive from the SAME advertised
    rows, so full splits exercise the complete credit balance)."""
    sched = CommSchedule("all_to_all", world, [[] for _ in range(world)],
                         meta={"nblk": nblk})
    # seed labels: rank me's outgoing segment for peer p, block b
    for me in range(world):
        for p in range(world):
            for b in range(nblk):
                sched.init.append((me, "send", p * nblk + b,
                                   ("tok", me, p, b)))
            sched.init.append((me, "splits", p, ("split", me, p)))
    sched.outputs = {"recv": world * nblk, "rsplits": world}
    for me in range(world):
        ops = sched.ranks[me]
        _a2a_round(ops, me, world, nblk=nblk, pfx="", step=0,
                   with_splits=True)
        _a2a_read_all(ops, me, world, nblk=nblk, pfx="", step=0,
                      note="post-process consume")
    return sched


# ---------------------------------------------------------------------------
# low_latency_allgather — one-shot full-mesh push (the fcollect verb)
# ---------------------------------------------------------------------------


@schedule_builder("low_latency_allgather")
def build_low_latency_allgather(world: int) -> CommSchedule:
    """``_full_mesh_push_ag_kernel`` (allgather.py:185-230) — the body
    of ``fast_allgather`` / ``dl.fcollect`` (primitives.py:205-238):
    stage my shard into my slot (overlapped with the entry barrier),
    push it to every peer, drain the ``world-1`` sends, then wait for
    the ``world-1`` incoming slots.  No credits: every slot is written
    exactly once."""
    sched = CommSchedule("low_latency_allgather", world,
                         [[] for _ in range(world)])
    for me in range(world):
        sched.init.append((me, "x", 0, ("seg", me)))
    sched.outputs = {"gath": world}
    for me in range(world):
        ops = sched.ranks[me]
        # Stage starts before the barrier, overlapping kernel entry.
        ops.append(Op("send", step=0, dst=me, src_buf="x", src_slot=0,
                      buf="gath", slot=me, rsem="copy",
                      label=("seg", me), final=True, note="stage"))
        _full_barrier(ops, me, world)
        for i in range(1, world):
            peer = (me + i) % world
            ops.append(Op("send", step=0, dst=peer, src_buf="x",
                          src_slot=0, buf="gath", slot=me, rsem="recv",
                          ssem="send", label=("seg", me), final=True))
        ops.append(Op("wait", step=0, sem="copy", note="stage done"))
        ops.append(Op("wait", step=0, sem="send", count=world - 1,
                      note="drain sends"))
        ops.append(Op("wait", step=0, sem="recv", count=world - 1,
                      note="peer slots arrived"))
        for j in range(world):
            ops.append(Op("read", step=0, buf="gath", slot=j,
                          label=("seg", j), note="consume gathered"))
    return sched


# ---------------------------------------------------------------------------
# ulysses_attention — two fused AllToAlls around local attention
# ---------------------------------------------------------------------------


@schedule_builder("ulysses_attention")
def build_ulysses_attention(world: int) -> CommSchedule:
    """``ulysses_attention`` (ulysses_attention.py): exactly two
    AllToAlls per call — Q/K/V ride ONE fused head-scatter (the
    ``fast_all_to_all`` kernel, = :func:`build_all_to_all`'s round at
    equal splits, nblk=1), local attention consumes every arrived head
    chunk, and the output rides the inverse scatter."""
    nblk = 1
    sched = CommSchedule("ulysses_attention", world,
                         [[] for _ in range(world)],
                         meta={"nblk": nblk})
    for me in range(world):
        for p in range(world):
            sched.init.append((me, "qkv_send", p, ("tok", me, p, 0)))
    sched.outputs = {"qkv_recv": world, "o_recv": world}
    for me in range(world):
        ops = sched.ranks[me]
        # A2A #1: head-scatter of the fused QKV (equal splits — no
        # split plane: the fused scatter ships fixed head chunks).
        _a2a_round(ops, me, world, nblk=nblk, pfx="qkv_", step=0,
                   with_splits=False)
        _a2a_read_all(ops, me, world, nblk=nblk, pfx="qkv_", step=0,
                      note="local attention")
        # Local attention writes the per-peer output chunks that ride
        # the inverse scatter.
        for p in range(world):
            ops.append(Op("write", step=1, buf="o_send", slot=p,
                          label=("tok", me, p, 0), note="attn output"))
        _a2a_round(ops, me, world, nblk=nblk, pfx="o_", step=2,
                   with_splits=False)
        _a2a_read_all(ops, me, world, nblk=nblk, pfx="o_", step=2,
                      note="restore sequence sharding")
    return sched


# ---------------------------------------------------------------------------
# sp_decode — the SP flash-decode combine (fcollect + in-kernel merge)
# ---------------------------------------------------------------------------


@schedule_builder("sp_decode")
def build_sp_decode(world: int) -> CommSchedule:
    """``_sp_combine_kernel`` (flash_decode.py:804-835): barrier, then
    the ``dl.fcollect`` gather round of the packed (out ⊕ lse) partial
    planes — push my plane to every peer's slot ``me``, stage my own,
    drain, wait arrivals — then the in-kernel LSE merge reads every
    slot and writes the final combined output."""
    sched = CommSchedule("sp_decode", world, [[] for _ in range(world)])
    for me in range(world):
        sched.init.append((me, "plane", 0, ("partial", me)))
    sched.outputs = {"gath": world, "final": 1}
    for me in range(world):
        ops = sched.ranks[me]
        _full_barrier(ops, me, world)
        # fcollect (primitives.py:205-238): peer pushes FIRST (they read
        # the input ref, independent of the staging copy), then the
        # local stage, drains, arrivals.
        for i in range(1, world):
            peer = (me + i) % world
            ops.append(Op("send", step=0, dst=peer, src_buf="plane",
                          src_slot=0, buf="gath", slot=me, rsem="recv",
                          ssem="send", label=("partial", me),
                          final=True))
        ops.append(Op("send", step=0, dst=me, src_buf="plane",
                      src_slot=0, buf="gath", slot=me, rsem="copy",
                      label=("partial", me), final=True, note="stage"))
        ops.append(Op("wait", step=0, sem="copy"))
        ops.append(Op("wait", step=0, sem="send", count=world - 1,
                      note="drain (quiet)"))
        ops.append(Op("wait", step=0, sem="recv", count=world - 1,
                      note="arrivals"))
        for j in range(world):
            ops.append(Op("read", step=0, buf="gath", slot=j,
                          label=("partial", j), note="LSE merge"))
        ops.append(Op("write", step=0, buf="final", slot=0,
                      label=("combined", me), final=True))
    return sched


# ---------------------------------------------------------------------------
# hier_sp_combine — two-phase (fast x slow) hierarchical SP combine
# ---------------------------------------------------------------------------


def _smallest_prime_factor(n: int) -> int:
    p = 2
    while p * p <= n:
        if n % p == 0:
            return p
        p += 1
    return n


@schedule_builder("hier_sp_combine")
def build_hier_sp_combine(world: int) -> CommSchedule:
    """The hierarchical two-phase LSE combine behind the 2D serving
    mesh (serve/mesh.py ``kv_shard="heads+seq"``): partials merge first
    inside a FAST group (ICI-near neighbours: ``fast`` = the smallest
    prime factor of ``world``), then the per-group merged planes ride a
    second fcollect across the SLOW axis (``slow = world // fast``)
    and the final merge combines them.  Each phase is one
    :func:`build_sp_decode`-shaped fcollect round restricted to its
    subgroup — rank ``me = g*fast + l`` gathers over ``l`` in phase 1
    and over ``g`` in phase 2.  LSE merging is associative, so the
    two-phase result is bit-wise the flat combine's up to the merge
    order the schedule fixes.  Prime worlds (3, 5, 7...) have
    ``slow == 1`` and degenerate to the single flat phase — the builder
    must stay correct there, not just on the pow2 grid.
    """
    fast = _smallest_prime_factor(world)
    slow = world // fast
    sched = CommSchedule("hier_sp_combine", world,
                         [[] for _ in range(world)],
                         meta={"fast": fast, "slow": slow})
    for me in range(world):
        sched.init.append((me, "plane", 0, ("partial", me)))
    sched.outputs = {"gath1": fast, "final": 1}
    if slow > 1:
        sched.outputs.update({"mid": 1, "gath2": slow})
    for me in range(world):
        g, l = divmod(me, fast)
        ops = sched.ranks[me]
        # ---- phase 1: fcollect + merge inside the fast group -------
        # entry barrier over the fast group only (the slow peers'
        # buffers are untouched until phase 2).
        for i in range(1, fast):
            ops.append(Op("signal", dst=g * fast + (l + i) % fast,
                          sem="barrier"))
        ops.append(Op("wait", sem="barrier", count=fast - 1))
        for i in range(1, fast):
            peer = g * fast + (l + i) % fast
            ops.append(Op("send", step=0, dst=peer, src_buf="plane",
                          src_slot=0, buf="gath1", slot=l, rsem="recv1",
                          ssem="send1", label=("partial", me),
                          final=True))
        ops.append(Op("send", step=0, dst=me, src_buf="plane",
                      src_slot=0, buf="gath1", slot=l, rsem="copy1",
                      label=("partial", me), final=True, note="stage"))
        ops.append(Op("wait", step=0, sem="copy1"))
        ops.append(Op("wait", step=0, sem="send1", count=fast - 1,
                      note="drain (quiet)"))
        ops.append(Op("wait", step=0, sem="recv1", count=fast - 1,
                      note="arrivals"))
        for j in range(fast):
            ops.append(Op("read", step=0, buf="gath1", slot=j,
                          label=("partial", g * fast + j),
                          note="LSE merge (fast)"))
        if slow == 1:
            # prime world: the fast group IS the world — phase 1's
            # merge is already the flat combine.
            ops.append(Op("write", step=0, buf="final", slot=0,
                          label=("combined", me), final=True))
            continue
        ops.append(Op("write", step=0, buf="mid", slot=0,
                      label=("mid", g), final=True,
                      note="group-merged plane"))
        # ---- phase 2: fcollect + merge across the slow axis --------
        for i in range(1, slow):
            ops.append(Op("signal", dst=((g + i) % slow) * fast + l,
                          sem="barrier2"))
        ops.append(Op("wait", sem="barrier2", count=slow - 1))
        for i in range(1, slow):
            peer = ((g + i) % slow) * fast + l
            ops.append(Op("send", step=1, dst=peer, src_buf="mid",
                          src_slot=0, buf="gath2", slot=g, rsem="recv2",
                          ssem="send2", label=("mid", g), final=True))
        ops.append(Op("send", step=1, dst=me, src_buf="mid",
                      src_slot=0, buf="gath2", slot=g, rsem="copy2",
                      label=("mid", g), final=True, note="stage"))
        ops.append(Op("wait", step=1, sem="copy2"))
        ops.append(Op("wait", step=1, sem="send2", count=slow - 1,
                      note="drain (quiet)"))
        ops.append(Op("wait", step=1, sem="recv2", count=slow - 1,
                      note="arrivals"))
        for j in range(slow):
            ops.append(Op("read", step=1, buf="gath2", slot=j,
                          label=("mid", j), note="LSE merge (slow)"))
        ops.append(Op("write", step=1, buf="final", slot=0,
                      label=("combined", me), final=True))
    return sched
