"""Network serving plane: a replica process as a complete network
citizen, and the fault-tolerant client that drives it.

PR 9's fleet controller routes, health-checks, and live-migrates only
IN-PROCESS replicas; the subprocess mode could scrape pressure but not
place a request or move one off a dead process.  This module closes
that gap (ROADMAP #4's open follow-up) with a deliberately boring
transport — HTTP/JSON over the stdlib, in the
``trace.start_metrics_server`` mold, no new dependency — and a
deliberately careful protocol: every mutating call is IDEMPOTENT, so a
retry whose first attempt actually landed is a no-op, never a duplicate
stream.

Server (:class:`ReplicaServer`, one per engine process):

========================  =================================================
endpoint                  semantics
========================  =================================================
``POST /submit``          submit one request; keyed by ``rid`` — a rid the
                          replica has ever seen answers ``dup: true``
                          without touching the engine
``GET  /stream``          ``?rid=R&since=N``: the delivery log from index
                          N on + finish state — delivery resumes from the
                          last index the CLIENT acknowledged, so a lost
                          response re-delivers but never re-derives
``POST /poll``            batched ``/stream`` (one round trip per tick)
``POST /drain``           migrate-out ``rids`` (KV pages ride base64);
                          carries an idempotency ``key`` — a retry returns
                          the CACHED manifest (the engine drained once,
                          the ``mig`` receipts stand), and a fresh drain
                          of already-receipted rids is EMPTY
``POST /migrate_in``      adopt a migration manifest; same ``key`` replay
                          rule, and a duplicate rid is rejected by the
                          engine's own capacity admission
``POST /push``            adopt a disaggregated prefill→decode PUSH
                          hand-off (``ServeEngine.admit_pushed``); same
                          ``key`` replay rule under its own cache kind,
                          so a lost ack can never double-admit
``GET  /health``          liveness + load snapshot (the router's signal);
                          ``ok`` goes false when the serve loop stopped
                          pumping — a wedged engine thread reads as down
                          even while the HTTP listener survives
``GET  /metrics``         the PR-8 Prometheus exposition
``POST /shutdown``        stop :func:`serve_loop` cleanly
========================  =================================================

Thread discipline: HTTP handler threads never touch the engine.  Reads
(`/stream`, `/health`) serve server-maintained state under a lock;
mutations enqueue a closure that :meth:`ReplicaServer.pump` — called by
the engine's OWN loop between steps — executes, so the engine stays
single-threaded exactly as every other driver keeps it.

Client (:class:`NetClient`): per-call timeouts, bounded retries under
jittered exponential backoff (:class:`serve.fleet.RestartBackoff` — the
same pacing law as replica restarts), and the deterministic ``net``
fault seams (``runtime/faults.py``: drop / delay / duplicate /
partition) on every call.  ``serve.fleet.RemoteReplica`` wraps it in
the engine protocol the :class:`~serve.fleet.FleetController` already
speaks.

See docs/serving.md "Network fleet serving" for the protocol, the
timeout/backoff policy, and the exactly-once-across-the-wire argument.
"""

from __future__ import annotations

import base64
import json
import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from triton_dist_tpu.runtime.faults import (
    CORRUPT_ACTIONS,
    InjectedNetFault,
    corrupt_bytes,
)
from triton_dist_tpu.serve.integrity import canonical_crc, crc32_bytes

#: Wire protocol version — both ends check it, so a stale replica binary
#: fails loud instead of mis-parsing.
NET_PROTOCOL = 1

#: Name of the file :func:`write_port_file` drops next to a replica's
#: snapshot dir so a spawning controller can discover the bound port.
PORT_FILE = "net_port"


class NetError(RuntimeError):
    """A network call failed after every retry — the transport-level
    verdict the caller maps onto the replica health ladder."""


class NetUnreachable(NetError):
    """The replica answered NO retry of a liveness-bearing call.  The
    fleet controller treats this as missing progress (SUSPECT after
    ``suspect_after_s``, DEAD after ``dead_after_s``) — NOT as an
    instant replica death: a transient partition must walk the same
    ladder a stall does."""


class NetHTTPError(NetError):
    """The replica ANSWERED with an HTTP error status — the transport
    worked, the request was wrong (unknown rid, bad format).  Never
    retried."""

    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body[:200]}")
        self.status = status
        self.body = body


class NetOverloaded(NetHTTPError):
    """The replica ANSWERED ``429 Too Many Requests``: admission
    pressure, not a transport fault and not a wrong request.  The
    client retries under its normal jittered ladder with the server's
    ``Retry-After`` hint as the delay FLOOR (jitter on top keeps a
    fleet's retries from synchronizing); exhausting the ladder
    surfaces this exception, which the caller maps onto the
    bounded-admission contract (``RemoteReplica.submit`` →
    ``QueueFull``, so the controller re-places or sheds).  The
    idempotency-key/rid replay cache is what makes every retry safe."""

    def __init__(self, status: int, body: str, *,
                 retry_after_s: float = 0.0):
        super().__init__(status, body)
        self.retry_after_s = retry_after_s


# ---------------------------------------------------------------------------
# Manifest wire form: KV pages as base64 so live hand-offs cross the wire
# ---------------------------------------------------------------------------


class ManifestCorrupt(ValueError):
    """A wire manifest failed digest verification on the RECEIVER —
    a KV blob's bytes or a request's metadata no longer match the
    sender's stamp.  Subclasses :class:`ValueError` so ``_route`` maps
    it to a definitive 400 (never retried verbatim); the sender's
    rejection fallback ladder (capacity walk → general placer →
    ``_no_push`` pin / crash-path re-placement) then re-routes the
    request through exact recompute.  Corruption is a re-queue, never
    adopted state — docs/serving.md "Durability & integrity"."""


#: request-metadata fields covered by the per-request wire digest
#: (``mdig``).  Deliberately the invariant core — rid, prompt, committed
#: tokens, sampling params — not the mutable transport envelope
#: (kv/kv_len/pending/s_ext are covered by their own per-blob CRCs or
#: recomputed on adoption), so the digest survives both the live-KV and
#: the journal-segment (save_manifest-stripped) forms.
MDIG_FIELDS = ("rid", "prompt", "tokens", "params")


def _req_mdig(rec: dict) -> int:
    return canonical_crc({k: rec[k] for k in MDIG_FIELDS if k in rec})


def _enc_arr(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    raw = a.tobytes()
    return {"__nd__": True, "dtype": str(a.dtype), "shape": list(a.shape),
            "crc": crc32_bytes(raw),
            "b64": base64.b64encode(raw).decode("ascii")}


def _dec_arr(d: dict) -> np.ndarray:
    try:
        raw = base64.b64decode(d["b64"], validate=True)
    except (ValueError, TypeError) as e:
        raise ManifestCorrupt(f"KV blob is not valid base64: {e}") from None
    want = d.get("crc")   # absent on pre-integrity senders: tolerated
    if want is not None and int(want) != crc32_bytes(raw):
        raise ManifestCorrupt(
            f"KV blob digest mismatch (stamped {want}, received "
            f"{crc32_bytes(raw)}) — rejecting the manifest; the sender "
            f"re-routes through exact recompute")
    try:
        return np.frombuffer(raw, dtype=np.dtype(d["dtype"])).reshape(
            d["shape"])
    except (ValueError, TypeError) as e:
        raise ManifestCorrupt(
            f"KV blob bytes do not fit dtype/shape: {e}") from None


def _enc_kv(x) -> dict:
    """One K or V cache element: a bare float array, or the quantized
    ``{"q", "s"}`` pair (int8 page bytes + their scale plane travel
    TOGETHER — the int8 payload is what halves the wire bytes of a
    drain or a disagg KV-page push)."""
    if isinstance(x, dict) and not x.get("__nd__"):
        return {"q": _enc_arr(np.asarray(x["q"])),
                "s": _enc_arr(np.asarray(x["s"]))}
    return _enc_arr(np.asarray(x))


def _dec_kv(x):
    if isinstance(x, dict) and x.get("__nd__"):
        return _dec_arr(x)
    if isinstance(x, dict) and "q" in x:
        return {"q": (_dec_arr(x["q"]) if isinstance(x["q"], dict)
                      and x["q"].get("__nd__") else np.asarray(x["q"])),
                "s": (_dec_arr(x["s"]) if isinstance(x["s"], dict)
                      and x["s"].get("__nd__") else np.asarray(x["s"]))}
    return np.asarray(x)


def encode_manifest(manifest: dict) -> dict:
    """JSON-safe form of a migration manifest: KV page payloads become
    base64 blobs (dtype + shape + bytes), everything else is already
    JSON — the wire twin of ``recovery.save_manifest`` that KEEPS the
    live pages, so a cross-process hand-off still adopts in place."""
    doc = dict(manifest)
    reqs = []
    for rec in manifest.get("requests", ()):
        rec = dict(rec)
        if rec.get("kv") is not None:
            rec["kv"] = [[_enc_kv(k), _enc_kv(v)] for k, v in rec["kv"]]
        rec["mdig"] = _req_mdig(rec)
        reqs.append(rec)
    doc["requests"] = reqs
    return doc


def decode_manifest(doc: dict) -> dict:
    """Inverse of :func:`encode_manifest` (idempotent on an
    already-decoded manifest).  Verifies every per-blob CRC and
    per-request ``mdig`` stamped by the sender, raising
    :class:`ManifestCorrupt` (→ definitive 400 on the server paths)
    BEFORE any state is adopted; manifests from pre-integrity senders
    carry no digests and decode unverified (mixed-fleet tolerance,
    ``NET_PROTOCOL`` unchanged)."""
    m = dict(doc)
    reqs = []
    for rec in m.get("requests", ()):
        rec = dict(rec)
        want = rec.pop("mdig", None)
        if want is not None and int(want) != _req_mdig(rec):
            raise ManifestCorrupt(
                f"request {rec.get('rid')!r}: metadata digest mismatch "
                f"— rejecting the manifest; the sender re-routes "
                f"through exact recompute")
        kv = rec.get("kv")
        if kv is not None:
            rec["kv"] = [(_dec_kv(k), _dec_kv(v)) for k, v in kv]
        reqs.append(rec)
    m["requests"] = reqs
    return m


def corrupt_wire_doc(doc: dict, action: str) -> dict:
    """Damage an ENCODED manifest in place of transport bit rot (the
    ``integrity`` fault point's wire-blob site — tests/bench only).
    Returns a DEEP copy with the first KV blob's payload bytes (or,
    when the manifest carries no KV, the first request's committed
    tokens) corrupted WITHOUT restamping the digests, so the receiver's
    :func:`decode_manifest` must detect and reject."""
    out = json.loads(json.dumps(doc))
    for rec in out.get("requests", ()):
        kv = rec.get("kv")
        if kv:
            blob = kv[0][0]
            if isinstance(blob, dict) and not blob.get("__nd__"):
                blob = blob["q"]   # quantized pair: damage the int8 plane
            raw = corrupt_bytes(base64.b64decode(blob["b64"]), action)
            blob["b64"] = base64.b64encode(raw).decode("ascii")
            return out
    for rec in out.get("requests", ()):
        if rec.get("tokens"):
            rec["tokens"] = rec["tokens"][:-1] + [rec["tokens"][-1] ^ 1]
            return out
    return out


def write_port_file(path: str, port: int) -> str:
    """Atomically publish the bound port (spawners poll for this)."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(f"{port}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_port_file(path: str, *, deadline_s: float = 30.0,
                   poll_s: float = 0.05) -> int:
    """Wait for a spawned replica to publish its port; raises
    :class:`NetError` past ``deadline_s`` (the spawner's readiness
    check must be bounded — a child that never comes up cannot hang
    the controller)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        try:
            with open(path, encoding="utf-8") as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            time.sleep(poll_s)
    raise NetError(f"no port published at {path} within {deadline_s}s")


# ---------------------------------------------------------------------------
# The replica server
# ---------------------------------------------------------------------------


class ReplicaServer:
    """The network ingest of ONE :class:`serve.engine.ServeEngine`
    (module docstring for the endpoint table and thread discipline).

    ``stall_after_s``: /health reports ``ok: false`` once the serve
    loop hasn't pumped for this long — the HTTP listener outliving a
    dead engine thread must not read as a healthy replica.
    ``faults``: a ``runtime.faults.FaultInjector`` whose ``net`` point
    fires at ``server_recv`` (before the request is processed — a drop
    here means it never arrived) and ``server_resp`` (after the action
    LANDED, before the answer is sent — a drop here is the lost ack the
    idempotent-retry semantics exist for)."""

    def __init__(self, engine, *, faults=None, stall_after_s: float = 10.0,
                 cache_entries: int = 32, cache_ttl_s: float = 120.0,
                 exec_timeout_s: float = 30.0,
                 streams_retain: int = 4096,
                 retry_after_s: float = 0.25):
        self.engine = engine
        self.faults = faults
        self.stall_after_s = stall_after_s
        # the Retry-After hint a 429 answer carries (seconds): how long
        # a submitting client should wait before re-offering — a full
        # queue drains on the decode timescale, not the RTT one
        self.retry_after_s = retry_after_s
        self.exec_timeout_s = exec_timeout_s
        self.streams_retain = streams_retain
        self._lock = threading.Lock()
        self._streams: dict[str, dict] = {}
        self._terminal: "OrderedDict[str, None]" = OrderedDict()
        self._cmds: queue.Queue = queue.Queue()
        self._cache: OrderedDict = OrderedDict()
        self._cache_entries = cache_entries
        self._cache_ttl_s = cache_ttl_s
        self._load: dict = {"ok": True}
        self._counts = {"requests": 0, "dups": 0, "redelivered": 0}
        self._last_pump = time.monotonic()
        self._shutdown = threading.Event()
        self._srv = None

    # -- engine-thread side ------------------------------------------------

    def _appender(self, rid: str) -> Callable:
        """The ``on_token`` the server hands the engine: append to the
        delivery log.  Fires AFTER the journal append (the PR 5
        ordering), so the log a client reads is always a prefix of the
        durable record — re-delivery can never outrun the journal."""
        def cb(_rid, tok):
            with self._lock:
                s = self._streams.get(rid)
                if s is not None:
                    s["tokens"].append(int(tok))
        return cb

    def _register(self, rid: str, tokens=()) -> None:
        with self._lock:
            self._terminal.pop(rid, None)   # live again: not prunable
            self._streams[rid] = {
                "tokens": [int(t) for t in tokens],
                "done": False, "reason": None, "error": None,
                "migrated": False, "served_hi": 0,
            }

    def _unregister(self, rid: str) -> None:
        with self._lock:
            self._streams.pop(rid, None)
            self._terminal.pop(rid, None)

    def _note_terminal(self, rid: str) -> None:
        """Bound the delivery-log map (lock held by the caller): done/
        migrated streams are kept for late re-polls and duplicate
        detection, but only the newest ``streams_retain`` of them —
        the engine's ``requests_retain`` twin.  A duplicate of a rid
        pruned here AND already pruned engine-side would re-serve; the
        retention window is the same tradeoff the engine already
        accepts."""
        self._terminal[rid] = None
        self._terminal.move_to_end(rid)
        while len(self._terminal) > self.streams_retain:
            old, _ = self._terminal.popitem(last=False)
            self._streams.pop(old, None)

    def publish(self, outs) -> None:
        """Record finished requests (engine thread, after ``step()``)."""
        with self._lock:
            for out in outs:
                s = self._streams.get(out.request_id)
                if s is None:
                    continue
                # the retirement's token list is authoritative (a
                # disabled callback starves the append path)
                if len(out.token_ids) > len(s["tokens"]):
                    s["tokens"] = [int(t) for t in out.token_ids]
                s["done"] = True
                s["reason"] = out.finish_reason.value
                s["error"] = out.error
                self._note_terminal(out.request_id)

    def pump(self, max_cmds: int = 64) -> int:
        """Execute queued mutations on the ENGINE thread (between
        steps), refresh the load snapshot, and fold the wire counters
        into the engine's metrics.  The serve loop calls this every
        iteration; handler threads only ever wait on it."""
        n = 0
        while n < max_cmds:
            try:
                fn, box = self._cmds.get_nowait()
            except queue.Empty:
                break
            try:
                box["result"] = fn()
            except Exception as e:      # handed to the waiting handler
                box["error"] = e        # thread (a 400/503 answer)
            except BaseException as e:  # noqa: BLE001 — InjectedKill /
                # interrupts ARE process death: answer the handler so
                # it doesn't hang, then let it escape — no containment
                # path may swallow it (runtime/faults.py contract), so
                # the serve loop (and the process) dies with it
                box["error"] = NetError(
                    f"replica dying: {type(e).__name__}: {e}")
                box["evt"].set()
                raise
            finally:
                box["evt"].set()
            n += 1
        eng = self.engine
        load = {
            "ok": True,
            "protocol": NET_PROTOCOL,
            "steps": eng.metrics.steps,
            "completed": eng.metrics.completed,
            "queue_depth": eng.scheduler.queue_depth,
            "running": sum(1 for s in eng.slots if s is not None),
            "max_batch": eng.max_batch,
            "max_queue": eng.max_queue,
            "kv_util": round(float(eng.bm.utilization), 6),
            "unfinished": len(eng.unfinished_rids()),
            # prefill-complete rows a disagg controller should push —
            # rides every health/poll answer so the PUSH trigger costs
            # no extra round trip (serve/disagg.py)
            "push_ready": eng.push_ready(),
        }
        with self._lock:
            self._load = load
            self._last_pump = time.monotonic()
            eng.metrics.net_requests = self._counts["requests"]
            eng.metrics.net_dup_hits = self._counts["dups"]
            eng.metrics.net_redelivered_tokens = self._counts["redelivered"]
        return n

    # -- handler-thread side ----------------------------------------------

    def _exec(self, fn):
        """Run ``fn`` on the engine thread via the command queue; the
        handler thread blocks until :meth:`pump` executes it.  A dead
        loop answers 503 after ``exec_timeout_s`` — the engine being
        gone must look like the replica being down, not a hang."""
        box = {"evt": threading.Event()}
        self._cmds.put((fn, box))
        if not box["evt"].wait(self.exec_timeout_s):
            raise NetError("engine loop not pumping (serve_loop dead "
                           "or wedged)")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _decode_verified(self, doc: dict, op: str) -> dict:
        """decode_manifest with the receiver-side rejection accounting:
        a digest mismatch counts ``manifest_corrupt``, emits the
        ``corrupt`` trace event, and re-raises — ``_route`` maps the
        :class:`ManifestCorrupt` (a ValueError) to a definitive 400,
        which the sender's fallback ladder turns into a re-queue
        through exact recompute.  Runs on the engine thread (inside
        ``_exec``), so touching engine.metrics/trace is safe."""
        if self.engine.faults is not None:
            act = self.engine.faults.fire("integrity", op=op)
            if act in CORRUPT_ACTIONS:
                doc = corrupt_wire_doc(doc, act)
        try:
            return decode_manifest(doc)
        except ManifestCorrupt as e:
            self.engine.metrics.manifest_corrupt += 1
            self.engine.trace.emit("corrupt", None, artifact="wire",
                                   op=op, why=str(e)[:200])
            raise

    def _cache_sweep(self) -> None:
        # TTL besides the count bound: a drain response pins its full
        # KV payload (base64) in memory, and the useful replay window
        # is the client's retry ladder (seconds) — a replica that
        # drains once must not carry that blob for the rest of its life
        cutoff = time.monotonic() - self._cache_ttl_s
        while self._cache:
            k = next(iter(self._cache))
            if self._cache[k][0] >= cutoff:
                break
            del self._cache[k]

    def _cached(self, kind: str, key: Optional[str]):
        if key is None:
            return None
        self._cache_sweep()
        hit = self._cache.get((kind, key))
        return hit[1] if hit is not None else None

    def _cache_put(self, kind: str, key: Optional[str], doc: dict) -> None:
        if key is None:
            return
        self._cache_sweep()
        self._cache[(kind, key)] = (time.monotonic(), doc)
        while len(self._cache) > self._cache_entries:
            self._cache.popitem(last=False)

    def handle_submit(self, doc: dict) -> dict:
        rid = doc["rid"]

        def do():
            # idempotency by request id: a rid this replica has EVER
            # seen (delivery log or engine state — the journal's view)
            # answers dup without re-entering the engine, so a retried
            # submit whose first attempt landed is a no-op
            with self._lock:
                known = rid in self._streams
            if known or self.engine.has_request(rid):
                self._counts["dups"] += 1
                return {"ok": True, "dup": True}
            from triton_dist_tpu.serve.engine import QueueFull
            from triton_dist_tpu.serve.request import (
                Request,
                SamplingParams,
            )

            self._register(rid)
            try:
                req = Request(
                    rid, np.asarray(doc["prompt"], np.int32),
                    SamplingParams.from_dict(doc["params"]),
                    on_token=self._appender(rid),
                    slo_class=doc.get("slo", "interactive"),
                    trace=doc.get("trace"))
                shed = self.engine.submit(req)
            except QueueFull as e:
                self._unregister(rid)
                return {"ok": False, "queue_full": True, "why": str(e),
                        "retry_after_s": self.retry_after_s}
            except Exception as e:  # noqa: BLE001 — an engine-rejected
                # submit (bad geometry, invalid params) must NOT leave
                # a ghost stream behind: it would answer dup:true to
                # every retry of a request the engine never accepted
                self._unregister(rid)
                return {"ok": False, "rejected": True,
                        "why": f"{type(e).__name__}: {e}"}
            if shed is not None:
                self.publish([shed])
                return {"ok": True, "shed": True,
                        "reason": shed.finish_reason.value,
                        "error": shed.error}
            return {"ok": True}
        return self._exec(do)

    def handle_stream(self, rid: str, since: int) -> Optional[dict]:
        with self._lock:
            s = self._streams.get(rid)
            if s is None:
                return None
            toks = s["tokens"][since:]
            redelivered = max(0, min(len(s["tokens"]), s["served_hi"])
                              - since)
            if redelivered:
                self._counts["redelivered"] += redelivered
            s["served_hi"] = max(s["served_hi"], len(s["tokens"]))
            return {"tokens": toks, "next": len(s["tokens"]),
                    "done": s["done"], "reason": s["reason"],
                    "error": s["error"], "migrated": s["migrated"]}

    def handle_poll(self, doc: dict) -> dict:
        out = {}
        for rid, since in doc.get("streams", {}).items():
            st = self.handle_stream(rid, int(since))
            out[rid] = st if st is not None else {"missing": True}
        # the health/load snapshot rides every poll: one round trip per
        # controller tick proves liveness AND refreshes the router's
        # pressure signal (a separate /health ping is only needed idle)
        return {"streams": out, "health": self.handle_health()}

    def handle_drain(self, doc: dict) -> dict:
        key = doc.get("key")

        def do():
            cached = self._cached("drain", key)
            if cached is not None:
                # the first attempt landed (mig receipts written, state
                # released) and only the ack was lost: replay the same
                # manifest — the engine is NOT drained twice
                self._counts["dups"] += 1
                return {**cached, "retried": True}
            present = set(self.engine.unfinished_rids())
            want = doc.get("rids")
            rids = [r for r in (want if want is not None
                                else sorted(present)) if r in present]
            m = self.engine.drain(rids,
                                  include_kv=doc.get("include_kv", True),
                                  push=doc.get("push", False))
            with self._lock:
                for r in rids:
                    s = self._streams.get(r)
                    if s is not None:
                        s["migrated"] = True
                        self._note_terminal(r)
            resp = {"ok": True, "manifest": encode_manifest(m)}
            self._cache_put("drain", key, resp)
            return resp
        return self._exec(do)

    def handle_migrate_in(self, doc: dict) -> dict:
        key = doc.get("key")

        def do():
            cached = self._cached("migrate_in", key)
            if cached is not None:
                self._counts["dups"] += 1
                return {**cached, "retried": True}
            m = self._decode_verified(doc["manifest"], "migrate_in")
            fresh, cbs = [], {}
            for rec in m.get("requests", ()):
                rid = rec["rid"]
                cbs[rid] = self._appender(rid)
                with self._lock:
                    s = self._streams.get(rid)
                    # a rid that migrated OUT and is now migrating back
                    # in restarts from the manifest's (newer) segment —
                    # its old entry is stale, not a duplicate
                    known = s is not None and not s["migrated"]
                if not known:
                    self._register(rid, tokens=rec.get("tokens", ()))
                    fresh.append(rid)
            try:
                res = self.engine.migrate_in(m, on_token=cbs)
            except Exception:
                # an engine-rejected manifest (format mismatch, bad
                # params) must not leave ghost streams behind — the
                # same cleanup handle_submit does; the error surfaces
                # to the client as a definitive 400
                for rid in fresh:
                    self._unregister(rid)
                raise
            for rid in res["rejected"]:
                if rid in fresh:
                    self._unregister(rid)
            resp = {"ok": True, "adopted": res["adopted"],
                    "requeued": res["requeued"],
                    "rejected": res["rejected"]}
            self._cache_put("migrate_in", key, resp)
            return resp
        return self._exec(do)

    def handle_push(self, doc: dict) -> dict:
        """Admit a prefill replica's PUSH manifest
        (``ServeEngine.admit_pushed`` — docs/serving.md "Disaggregated
        serving").  The same idempotency-key replay cache as
        /migrate_in, under its own cache kind: a retried push whose
        first attempt landed replays the cached admission verdict, so a
        lost ack can never double-admit a request."""
        key = doc.get("key")

        def do():
            cached = self._cached("push", key)
            if cached is not None:
                self._counts["dups"] += 1
                return {**cached, "retried": True}
            m = self._decode_verified(doc["manifest"], "push")
            fresh, cbs = [], {}
            for rec in m.get("requests", ()):
                rid = rec["rid"]
                cbs[rid] = self._appender(rid)
                with self._lock:
                    s = self._streams.get(rid)
                    known = s is not None and not s["migrated"]
                if not known:
                    self._register(rid, tokens=rec.get("tokens", ()))
                    fresh.append(rid)
            try:
                res = self.engine.admit_pushed(m, on_token=cbs)
            except Exception:
                # same ghost-stream cleanup as handle_migrate_in: an
                # engine-rejected manifest surfaces as a definitive 400
                for rid in fresh:
                    self._unregister(rid)
                raise
            for rid in res["rejected"]:
                if rid in fresh:
                    self._unregister(rid)
            resp = {"ok": True, "adopted": res["adopted"],
                    "requeued": res["requeued"],
                    "rejected": res["rejected"]}
            self._cache_put("push", key, resp)
            return resp
        return self._exec(do)

    def handle_health(self) -> dict:
        with self._lock:
            load = dict(self._load)
            age = time.monotonic() - self._last_pump
        if age > self.stall_after_s:
            load["ok"] = False
            load["why"] = f"serve loop silent {age:.1f}s"
        return load

    # -- lifecycle ---------------------------------------------------------

    def request_shutdown(self) -> None:
        self._shutdown.set()

    @property
    def shutdown_requested(self) -> bool:
        return self._shutdown.is_set()

    def start(self, port: int = 0, host: str = "127.0.0.1"):
        """Bind and serve from daemon threads; returns the HTTP server
        (``.server_address[1]`` is the bound port)."""
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _body(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(n) if n else b"{}"
                return json.loads(raw.decode("utf-8"))

            def _reply(self, code: int, doc: dict):
                if "__raw__" in doc:   # /metrics: exposition TEXT, not
                    #                    JSON — a Prometheus scraper
                    #                    reads this body directly
                    body = doc["__raw__"].encode("utf-8")
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = json.dumps(doc).encode("utf-8")
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if (code == 429
                        and doc.get("retry_after_s") is not None):
                    self.send_header(
                        "Retry-After", f"{doc['retry_after_s']:.3f}")
                self.end_headers()
                self.wfile.write(body)

            def _abort(self):
                # a dropped packet: no response ever leaves — the
                # client sees the connection die and retries
                self.close_connection = True

            def _route(self, method: str):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                op = path.lstrip("/")
                with outer._lock:
                    outer._counts["requests"] += 1
                if outer.faults is not None:
                    try:
                        outer.faults.fire("net", op=op,
                                          where="server_recv")
                    except InjectedNetFault:
                        return self._abort()
                try:
                    doc, code = self._dispatch(method, path)
                except NetError as e:
                    doc, code = {"ok": False, "error": str(e)}, 503
                except (KeyError, ValueError, TypeError) as e:
                    doc, code = {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"}, 400
                if outer.faults is not None:
                    try:
                        outer.faults.fire("net", op=op,
                                          where="server_resp")
                    except InjectedNetFault:
                        return self._abort()   # the action landed; the
                        #                        ack is lost
                self._reply(code, doc)

            def _dispatch(self, method: str, path: str):
                if method == "GET" and path == "/health":
                    return outer.handle_health(), 200
                if method == "GET" and path == "/metrics":
                    # rendered on the ENGINE thread via the pump: the
                    # exposition iterates live counter maps, and the
                    # handler-threads-never-touch-the-engine rule is
                    # what keeps those reads untorn
                    text = outer._exec(
                        outer.engine.metrics.to_prometheus)
                    return {"__raw__": text}, 200
                if method == "GET" and path == "/stream":
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    rid = q.get("rid", [None])[0]
                    since = int(q.get("since", ["0"])[0])
                    st = outer.handle_stream(rid, since)
                    if st is None:
                        return {"ok": False,
                                "error": f"unknown rid {rid!r}"}, 404
                    return st, 200
                if method == "POST" and path == "/poll":
                    return outer.handle_poll(self._body()), 200
                if method == "POST" and path == "/submit":
                    doc = outer.handle_submit(self._body())
                    if doc.get("queue_full"):
                        # overload is 429 + Retry-After, not a 200: the
                        # client's backoff ladder paces itself on the
                        # hint instead of reading pressure as transport
                        # trouble (docs/serving.md "Overload")
                        return doc, 429
                    return doc, 200
                if method == "POST" and path == "/drain":
                    return outer.handle_drain(self._body()), 200
                if method == "POST" and path == "/migrate_in":
                    return outer.handle_migrate_in(self._body()), 200
                if method == "POST" and path == "/push":
                    return outer.handle_push(self._body()), 200
                if method == "POST" and path == "/shutdown":
                    outer.request_shutdown()
                    return {"ok": True}, 200
                return {"ok": False, "error": f"no route {path}"}, 404

            def do_GET(self):      # noqa: N802 — stdlib contract
                self._route("GET")

            def do_POST(self):     # noqa: N802
                self._route("POST")

            def log_message(self, *args):
                pass

        srv = http.server.ThreadingHTTPServer((host, port), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever, daemon=True,
                             name="serve-net")
        t.start()
        self._srv = srv
        return srv

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def close(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None


def serve_loop(engine, server: ReplicaServer, *,
               idle_sleep_s: float = 0.005,
               step_sleep_s: float = 0.0,
               exit_when_idle_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               max_steps: Optional[int] = None) -> int:
    """Drive one engine behind its :class:`ReplicaServer`: pump queued
    network mutations, step while there is work, publish retirements,
    beat the heartbeat while idle.  Returns the step count.

    Exits on ``POST /shutdown``, after ``exit_when_idle_s`` of no work
    (demo/test hygiene), past ``deadline_s`` of wall clock (the bounded
    lifetime a chaos harness gives a child so a wedged replica can
    never outlive its test), or at ``max_steps``.  Anything escaping
    ``engine.step()`` — ``InjectedKill`` included — propagates: a
    dying engine takes the loop (and the process) with it, exactly
    like every other driver."""
    t0 = time.monotonic()
    last_work = t0
    steps = 0
    while not server.shutdown_requested:
        now = time.monotonic()
        if deadline_s is not None and now - t0 > deadline_s:
            break
        server.pump()
        if engine.has_work():
            outs = engine.step()
            server.publish(outs)
            steps += 1
            last_work = time.monotonic()
            if max_steps is not None and steps >= max_steps:
                break
            if step_sleep_s:
                # test/bench throttle: a tiny model outruns its own
                # chaos harness — pacing steps keeps a mid-decode
                # window open wide enough to kill a replica inside it
                time.sleep(step_sleep_s)
        else:
            engine._beat()  # idle is alive: the supervisor's stall
            #                 detector must not read "no work" as "wedged"
            if (exit_when_idle_s is not None
                    and now - last_work > exit_when_idle_s):
                break
            time.sleep(idle_sleep_s)
    server.pump()   # drain the command queue: late handlers get answers
    return steps


# ---------------------------------------------------------------------------
# The client transport
# ---------------------------------------------------------------------------


class NetClient:
    """HTTP/JSON calls with per-call timeouts and bounded retries under
    jittered exponential backoff (the :class:`serve.fleet.RestartBackoff`
    pacing law — restarts and retries must not synchronize across a
    fleet for the same reason).

    Retry discipline: transport failures (refused, reset, timed out,
    injected drop/partition) retry up to ``retries`` times; HTTP-level
    errors (the replica ANSWERED: 404, 400) raise
    :class:`NetHTTPError` immediately — a wrong request does not become
    right by asking again.  Every retry invokes ``on_retry(op, attempt,
    delay_s, error)`` so the caller can surface the backoff ladder
    (``net_retry`` trace events, audit entries).

    The ``net`` fault point fires once per send at the ``client`` seam
    (``op=`` the endpoint, ``target=`` this client's peer name):
    ``drop``/``partition`` raise before the request leaves,
    ``delay_s`` stalls it, ``duplicate`` makes this transport send the
    request TWICE — the server's idempotency is what keeps that safe.
    """

    def __init__(self, url: str, *, name: Optional[str] = None,
                 timeout_s: float = 5.0, retries: int = 3,
                 retry_base_s: float = 0.05, retry_cap_s: float = 2.0,
                 retry_jitter: float = 0.5, seed: int = 0,
                 faults=None, on_retry: Optional[Callable] = None):
        self.url = url.rstrip("/")
        self.name = name or self.url
        self.timeout_s = timeout_s
        self.retries = retries
        self.retry_base_s = retry_base_s
        self.retry_cap_s = retry_cap_s
        self.retry_jitter = retry_jitter
        self.seed = seed
        self.faults = faults
        self.on_retry = on_retry
        self._calls = 0

    def _http(self, method: str, path: str,
              payload: Optional[bytes], timeout_s: float) -> dict:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.url + path, data=payload, method=method,
            headers={"Content-Type": "application/json"}
            if payload is not None else {})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            body = ""
            try:
                body = e.read().decode("utf-8", "replace")
            except Exception:  # noqa: BLE001 — body is best-effort
                pass
            if e.code == 503:
                raise ConnectionError(f"replica busy/dead: {body[:100]}")
            if e.code == 429:
                try:
                    ra = float(e.headers.get("Retry-After") or 0.0)
                except (TypeError, ValueError):
                    ra = 0.0
                raise NetOverloaded(e.code, body, retry_after_s=ra)
            raise NetHTTPError(e.code, body)

    def call(self, op: str, path: str, *, method: str = "GET",
             body: Optional[dict] = None,
             timeout_s: Optional[float] = None,
             retries: Optional[int] = None) -> dict:
        """One logical call, retried to completion or :class:`NetError`.
        ``retries=0`` makes it a single probe (liveness pings use it:
        the fleet loop is single-threaded, so a blackholed replica must
        cost one short timeout per tick, not a whole retry ladder)."""
        from triton_dist_tpu.serve.fleet import RestartBackoff

        payload = (json.dumps(body).encode("utf-8")
                   if body is not None else None)
        timeout_s = timeout_s if timeout_s is not None else self.timeout_s
        self._calls += 1
        bo = RestartBackoff(base_s=self.retry_base_s,
                            cap_s=self.retry_cap_s,
                            jitter=self.retry_jitter,
                            max_restarts=(self.retries if retries is None
                                          else retries),
                            seed=self.seed + self._calls)
        attempt = 0
        while True:
            attempt += 1
            try:
                action = None
                if self.faults is not None:
                    action = self.faults.fire("net", op=op,
                                              target=self.name,
                                              where="client")
                resp = self._http(method, path, payload, timeout_s)
                if action == "duplicate":
                    # the network's duplicate delivery: send the SAME
                    # request again — the server must dedupe, and the
                    # duplicate's fate is irrelevant to this caller
                    # (ANY failure of it must not discard the first,
                    # successful exchange)
                    try:
                        self._http(method, path, payload, timeout_s)
                    except Exception:  # noqa: BLE001
                        pass
                return resp
            except NetOverloaded as e:
                # 429: retry under the SAME jittered ladder, but never
                # sooner than the server's Retry-After hint — pressure
                # is answered with patience, not with a tighter loop.
                # An exhausted ladder surfaces the NetOverloaded for
                # the caller's bounded-admission mapping.
                delay = bo.on_death(time.monotonic())
                if delay is None:
                    raise
                delay = max(delay, e.retry_after_s)
                if self.on_retry is not None:
                    self.on_retry(op, attempt, delay,
                                  f"overloaded (retry after "
                                  f"{e.retry_after_s:g}s)")
                time.sleep(delay)
            except NetHTTPError:
                raise
            except (InjectedNetFault, OSError,
                    json.JSONDecodeError) as e:
                # OSError covers refused/reset/timeout and the stdlib
                # http.client exceptions' common transport base cases;
                # a half-written response parses as JSONDecodeError
                delay = bo.on_death(time.monotonic())
                if delay is None:
                    raise NetError(
                        f"{op} {self.url}{path}: {attempt} attempts "
                        f"failed; last: {type(e).__name__}: {e}") from e
                if self.on_retry is not None:
                    self.on_retry(op, attempt, delay,
                                  f"{type(e).__name__}: {e}")
                time.sleep(delay)
            except Exception as e:  # noqa: BLE001 — http.client raises
                # protocol exceptions (RemoteDisconnected,
                # BadStatusLine) that do not derive from OSError
                import http.client
                if not isinstance(e, http.client.HTTPException):
                    raise
                delay = bo.on_death(time.monotonic())
                if delay is None:
                    raise NetError(
                        f"{op} {self.url}{path}: {attempt} attempts "
                        f"failed; last: {type(e).__name__}: {e}") from e
                if self.on_retry is not None:
                    self.on_retry(op, attempt, delay,
                                  f"{type(e).__name__}: {e}")
                time.sleep(delay)


# ---------------------------------------------------------------------------
# In-process replica: serve_loop on a thread — the subprocess stand-in
# the bench + fast-gate tests drive (same wire, no spawn cost)
# ---------------------------------------------------------------------------


class InProcessReplica:
    """One engine + :class:`ReplicaServer` + ``serve_loop`` thread: a
    replica 'process' that lives in this process but is reachable ONLY
    through the wire — the unit-test / bench stand-in for a subprocess
    replica (the chaos harness in tests/test_serve_net.py runs real
    processes; everything else exercises the identical protocol here).

    ``kill()`` is the SIGKILL analog: stop the loop, join the thread,
    close the engine's journal (restoring the single-writer invariant
    the crash-path ``mig`` mark needs), and tear the listener down so
    clients see connection-refused like a dead process."""

    def __init__(self, engine, *, faults=None,
                 stall_after_s: float = 10.0, port: int = 0,
                 step_sleep_s: float = 0.0,
                 streams_retain: int = 4096):
        self.engine = engine
        self.server = ReplicaServer(engine, faults=faults,
                                    stall_after_s=stall_after_s,
                                    streams_retain=streams_retain)
        self.server.start(port=port)
        self._step_sleep_s = step_sleep_s
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="inproc-replica")
        self.died: Optional[BaseException] = None
        self._thread.start()

    def _run(self):
        try:
            serve_loop(self.engine, self.server,
                       step_sleep_s=self._step_sleep_s)
        except BaseException as e:  # noqa: BLE001 — a dying engine
            self.died = e           # kills the 'process'; record why

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.server.port}"

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def kill(self) -> None:
        self.server.request_shutdown()
        self._thread.join(timeout=10.0)
        self.server.close()
        if self.engine._journal is not None:
            self.engine._journal.close()
