"""Continuous-batching serving engine over the paged-KV decode kernels.

The serving-side system the kernel layer was built for: iteration-level
continuous batching (Orca, OSDI '22) over a paged KV cache (vLLM's
PagedAttention, SOSP '23), orchestrating the primitives that already
exist below it — `kernels/flash_decode.gqa_decode_paged_shard` (block
tables ride scalar prefetch), `Generator.prefill_chunked` (bounded-memory
prompt streaming), the per-row ``active`` masks and multi-token ``q_lens``
verify contract (r5).

Layout:

- ``request``    — request/response dataclasses + sampling params
- ``block_manager`` — the paged KV block allocator (free list, per-request
  block tables, utilization accounting) + the content-addressed prefix
  cache: ref-counted blocks keyed by ``(parent block, token ids)``
  chains, copy-on-write sharing, an LRU-evictable warm cache tier
  (docs/serving.md "Prefix caching")
- ``scheduler``  — iteration-level FCFS admission + chunked-prefill token
  budget + LIFO preemption policy
- ``engine``     — the step loop: deadline sweep → admit → prefill
  chunks → one batched decode (a fused multi-step decode horizon with
  on-device sampling when ``horizon > 1``, or a speculative verify
  round) per iteration, with failure containment throughout
  (poison-request quarantine, watchdog-guarded dispatches, heartbeat;
  docs/serving.md "Failure containment" / "Decode horizon")
- ``metrics``    — TTFT / inter-token latency / queue depth / KV-block
  utilization / preemptions / failure counters, exported through
  runtime/dump.py
- ``recovery``   — crash resilience: engine snapshot/restore over the
  runtime/checkpoint Orbax path + the append-per-commit token journal
  with exactly-once resumption (docs/serving.md "Crash recovery")
- ``trace``      — the flight recorder: a bounded ring of typed engine
  events reconstructing per-request lifecycle spans (Perfetto export,
  merged with device traces via runtime/profiling.py), log-bucketed
  SLO histograms, the Prometheus exposition endpoint, and postmortem
  ``flight_<step>.json`` flushes on fault/crash paths
  (docs/observability.md)
- ``fleet``      — multi-replica serving: an admission router placing
  by queue-depth/deadline pressure, per-replica HEALTHY→SUSPECT→DEAD
  health with circuit breaking and backoff restarts, and live request
  migration over the journal/snapshot hand-off
  (docs/serving.md "Fleet serving")
- ``disagg``     — disaggregated prefill→decode serving: role-aware
  routing (prefill/decode/both replicas) and the per-request KV-page
  PUSH at prefill completion — in-place adoption on the stamped decode
  target, capacity-walk + general-placer fallbacks so no request is
  ever lost (docs/serving.md "Disaggregated serving")
- ``mesh``       — sharded serving: every engine device program as a
  ``shard_map`` body (TP weights + head-sharded pools, or replicated
  weights + block-sharded pools through the SP flash-decode combine),
  with canonical argument placement so the executable cache never
  forks (docs/serving.md "Sharded serving")
"""

from triton_dist_tpu.serve.request import (  # noqa: F401
    FinishReason,
    Request,
    RequestOutput,
    SamplingParams,
)
from triton_dist_tpu.serve.block_manager import BlockManager  # noqa: F401
from triton_dist_tpu.serve.scheduler import FCFSScheduler  # noqa: F401
from triton_dist_tpu.serve.metrics import (  # noqa: F401
    RequestMetrics,
    ServeMetrics,
    format_statline,
    format_stats,
)
from triton_dist_tpu.serve.trace import (  # noqa: F401
    FlightRecorder,
    LogHistogram,
    start_metrics_server,
)
from triton_dist_tpu.serve.recovery import (  # noqa: F401
    TokenJournal,
    has_restorable_state,
    replay_journal,
)
from triton_dist_tpu.serve.engine import (  # noqa: F401
    ChainCommitted,
    QueueFull,
    ServeEngine,
)
from triton_dist_tpu.serve.fleet import (  # noqa: F401
    FleetController,
    ReplicaState,
    RestartBackoff,
    Router,
)
from triton_dist_tpu.serve.disagg import (  # noqa: F401
    DisaggController,
    parse_disagg,
)
