"""Disaggregated prefill→decode serving: role-aware routing with
per-request KV-page PUSH.

The fleet layer (serve/fleet.py) treats every replica as interchangeable
— good for availability, bad for interference: one long-prompt prefill
stalls every decode sharing its batch, and the PR-7/PR-11 ITL
percentiles eat it.  The DistServe/Splitwise answer is to SPLIT the
tier: prefill replicas absorb the compute-bound bursts, decode replicas
run steady memory-bound token generation, and a request's KV pages move
from the one to the other exactly once, at prefill completion — the TPU
analog of the reference's producer/consumer signal-and-put hand-off,
applied at the serving tier instead of inside a kernel.

This module adds exactly that on top of the existing machinery, re-using
the migration substrate instead of inventing a second transport:

- **Roles** — :class:`~serve.fleet.FleetController` grows a ``role`` per
  replica (``prefill`` | ``decode`` | ``both``; default ``both`` keeps
  homogeneous fleets bit-identical).  Roles are routing POLICY, not
  capability: submits prefer the prefill pool by least-pressure,
  migrated/pushed records prefer decode-capable replicas, and
  availability always beats policy — a lone surviving replica of either
  role serves everything rather than strand work.

- **Per-request PUSH** — when a prefill replica finishes a request's
  prompt chunks (the row reaches RUNNING with a pending first token —
  ``ServeEngine.push_ready``), the controller extracts its single-request
  hand-off (``push_out``: the journal segment + live KV pages via the
  same ``load_pages`` gather ``drain`` uses, framed as ``push_out`` in
  the ring) and offers it to the request's pre-stamped decode target
  (``admit_pushed``): capacity admission first, then IN-PLACE adoption —
  ``fill_pages`` scatter, the row resumes RUNNING at its exact stream
  position with the pending-token invariant, zero recompute.  Cross
  process the pair rides ``POST /push`` with the NetClient retry ladder
  and an idempotency-key replay cache, so a lost ack can never
  double-admit.

- **No request is ever lost** — the decode target is chosen at admission
  and re-chosen on decode-replica death; a rejecting target sends the
  controller down the decode ranking; if EVERY decode-capable replica
  rejects, the record falls back to the general placer (any healthy
  replica — the source included — adopts it, exact recompute in the
  worst case).  Exactly-once holds by the same journal argument as
  migration: the source journals ``mig`` receipts before the manifest
  leaves, the target journals the carried segment before serving
  resumes, and the cross-journal union owns every token once.

Every push decision lands in the router audit (``kind="push"`` /
``"decode_target"``) so ``FleetController.explain(rid)`` answers "why
did it decode there" with the pressures and the rejected-capacity walk.

See docs/serving.md "Disaggregated serving" for the operator recipe and
the idempotency argument; ``examples/serve.py --disagg P:D`` and
``scripts/bench_serve.py --disagg P:D`` drive it.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from triton_dist_tpu.serve.fleet import (
    FleetController,
    ReplicaState,
    _manifest_header,
)
from triton_dist_tpu.serve.net import NetError
from triton_dist_tpu.serve.request import Request


def parse_disagg(spec: str) -> tuple[int, int]:
    """``"P:D"`` → ``(prefill, decode)`` replica counts, both >= 1 —
    the CLI shape of a disagg tier (``--disagg 2:2``)."""
    parts = str(spec).split(":")
    if len(parts) != 2:
        raise ValueError(
            f"--disagg wants PREFILL:DECODE (e.g. 1:2), got {spec!r}")
    try:
        p, d = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"--disagg wants integer counts, got {spec!r}") from None
    if p < 1 or d < 1:
        raise ValueError(
            f"--disagg needs >= 1 replica per role, got {spec!r}")
    return p, d


class DisaggController(FleetController):
    """A :class:`FleetController` whose fleet is a two-role tier:
    replicas ``r0..r{P-1}`` hold role ``prefill``, ``r{P}..r{P+D-1}``
    hold ``decode`` (module docstring; docs/serving.md "Disaggregated
    serving").

    Drive it exactly like the base controller — :meth:`submit` then
    :meth:`step`/``run`` — plus, each tick after the replicas step, the
    controller sweeps the prefill tier for prefill-complete rows and
    pushes each to its stamped decode target.  Extra state:

    - :attr:`decode_targets` — rid → the decode replica stamped at
      admission (re-stamped when that replica dies or rejects);
    - :attr:`pushes` / :attr:`push_fallbacks` — hand-offs completed /
      hand-offs that exhausted the decode ranking and fell back to the
      general placer.
    """

    def __init__(self, factory: Callable, prefill: int, decode: int, *,
                 root: str, **kw):
        if "roles" in kw:
            raise ValueError(
                "DisaggController derives roles from the prefill/decode "
                "counts; pass counts, not a roles map")
        if prefill < 1 or decode < 1:
            raise ValueError(
                f"need >= 1 replica per role, got "
                f"prefill={prefill}, decode={decode}")
        roles = {f"r{i}": ("prefill" if i < prefill else "decode")
                 for i in range(prefill + decode)}
        super().__init__(factory, prefill + decode, root=root,
                         roles=roles, **kw)
        self.n_prefill = prefill
        self.n_decode = decode
        #: rid -> decode replica chosen at admission (None while no
        #: decode-capable replica is healthy; re-stamped at push time)
        self.decode_targets: dict[str, Optional[str]] = {}
        self.pushes = 0
        self.push_fallbacks = 0
        # submitted Request objects, kept until retirement: the orphan
        # rescue (below) rebuilds a requeue record from prompt + params
        # + the delivered stream when a crash window leaves a request
        # with no owner
        self._reqs: dict[str, Request] = {}
        # rids whose push exhausted the decode ranking: they stay on
        # their fallback placement (every later tick would re-offer to
        # the same full pool — churn, not progress) until retirement
        self._no_push: set[str] = set()
        # per-tier autoscaler trackers: prefill and decode scale on
        # INDEPENDENT smoothed-pressure signals (a prompt burst must
        # grow the prefill tier without inflating decode, and vice
        # versa) — the base controller's single tracker becomes the
        # max-of-tiers gauge
        self._role_scale = {
            role: {"ema": 0.0, "t": None, "dwell": 0}
            for role in ("prefill", "decode")}

    # -- autoscaling (per tier) --------------------------------------------

    def _autoscale_step(self, now: float) -> None:
        for role in ("prefill", "decode"):
            reps = [(n, r) for n, r in self.replicas.items()
                    if r.role == role]
            # fresh unplaced work waits on prefill capacity; parked
            # migration/push records wait on decode capacity
            pending = (bool(self._pending_reqs) if role == "prefill"
                       else bool(self._pending_recs))
            spawned, retired = self._autoscale_tier(
                now, self._role_scale[role], reps, role=role,
                pending=pending)
            delta = (1 if spawned else 0) - (1 if retired else 0)
            if role == "prefill":
                self.n_prefill += delta
            else:
                self.n_decode += delta
        # the fleet-level gauge reports the hotter tier
        self._scale_state["ema"] = max(
            s["ema"] for s in self._role_scale.values())
        self._scale_state["t"] = now

    # -- admission ---------------------------------------------------------

    def submit(self, req: Request) -> None:
        super().submit(req)
        rid = req.request_id
        self._reqs[rid] = req
        self._stamp_decode_target(rid)

    def _stamp_decode_target(self, rid: str,
                             exclude: frozenset = frozenset()
                             ) -> Optional[str]:
        """Choose (or re-choose) ``rid``'s decode replica by
        least-pressure over the healthy decode pool, and audit the
        choice (``kind="decode_target"``) so ``explain(rid)`` shows why
        the decode landed where it did."""
        cands = [(n, l) for n, l in self._healthy("decode")
                 if n not in exclude]
        pressures = ({n: round(self.router.pressure(l), 4)
                      for n, l in cands}
                     if self.audit.enabled else None)
        target = self.router.pick(cands) if cands else None
        self.decode_targets[rid] = target
        if self.audit.enabled:
            self.audit.record(self._clock(), self.steps, "decode_target",
                              rid, chosen=target, pressures=pressures)
        return target

    # -- the tick ----------------------------------------------------------

    def step(self) -> list:
        outs = super().step()
        self._sweep_pushes()
        for rid in [r for r in self._reqs if r in self.outputs]:
            self._reqs.pop(rid, None)
            self.decode_targets.pop(rid, None)
            self._no_push.discard(rid)
        return outs

    def _sweep_pushes(self) -> None:
        """Push every prefill-complete row off the prefill tier.  A row
        is ready once it is RUNNING with a pending token — prefill done,
        first token sampled — so the decode replica adopts it IN PLACE
        and generates every remaining token (``ServeEngine.push_ready``;
        the remote twin reads the last health answer)."""
        for name, rep in self.replicas.items():
            if (rep.role != "prefill"
                    or rep.state is not ReplicaState.HEALTHY
                    or rep.engine is None):
                continue
            for rid in list(rep.engine.push_ready()):
                if self.placement.get(rid) != name:
                    continue   # moved or retired since the snapshot
                if rid in self._no_push:
                    continue   # already fell back; stay put
                self._push_request(name, rep, rid)

    def _push_request(self, name: str, rep, rid: str) -> None:
        target = self.decode_targets.get(rid)
        trep = self.replicas.get(target) if target is not None else None
        if (trep is None or target == name
                or trep.state is not ReplicaState.HEALTHY):
            target = self._stamp_decode_target(
                rid, exclude=frozenset((name,)))
        try:
            m = rep.engine.push_out(rid)
        except NetError:
            # unreachable mid-push: retry next tick — the drain
            # idempotency key replays a landed-but-unacked extraction,
            # and a death instead resolves through the journal
            return
        recs = m.get("requests", ())
        if not recs:
            return   # raced a retirement (remote push_ready is stale)
        header = _manifest_header(m)
        for rec in recs:
            prid = rec["rid"]
            # fill the delivery record from the manifest's journal
            # segment (the remote poll may lag the drained tokens —
            # same journal-precedes-callback argument as
            # _absorb_manifest)
            stream = self.streams.get(prid)
            toks = rec.get("tokens", [])
            if stream is not None:
                d = len(stream)
                assert d <= len(toks), (
                    f"{prid}: delivered {d} tokens but the push "
                    f"manifest only holds {len(toks)}")
                stream.extend(int(t) for t in toks[d:])
            self.placement.pop(prid, None)
            if not self._place_push(header, rec, preferred=target):
                self._pending_recs.append(
                    (header, rec, self._rec_expiry(header, rec)))

    def _place_push(self, header: dict, rec: dict, *,
                    preferred: Optional[str]) -> bool:
        """Offer one PUSH record to the decode pool — the stamped
        target first, then the decode ranking; a rejecting replica
        (capacity admission) passes it along.  Exhausting the pool
        falls back to the general placer: ANY healthy replica — the
        source included — adopts it rather than lose the request
        (exact recompute in the worst case; the manifest still carries
        KV, so even the fallback usually adopts in place)."""
        rid = rec["rid"]
        cands = self._healthy("decode")
        pressures = ({n: round(self.router.pressure(l), 4)
                      for n, l in cands}
                     if self.audit.enabled else None)
        rest = [(n, l) for n, l in cands if n != preferred]
        order = ([preferred] if any(n == preferred for n, _ in cands)
                 else [])
        if rest:
            order += self.router.rank(rest)
        rejected = {}
        for cname in order:
            crep = self.replicas[cname]
            res = crep.engine.admit_pushed(
                {**header, "requests": [rec]},
                on_token={rid: self._cbs.get(rid)})
            if rid in res["rejected"]:
                rejected[cname] = res["rejected"][rid]
                continue
            self.pushes += 1
            in_place = rid in res["adopted"]
            self.trace.emit("push_in", rid, replica=cname,
                            state=crep.state.value, in_place=in_place)
            if self.audit.enabled:
                self.audit.record(self._clock(), self.steps, "push",
                                  rid, chosen=cname, target=preferred,
                                  in_place=in_place,
                                  pressures=pressures,
                                  rejected=rejected)
            self.placement[rid] = cname
            self.history[rid].append(cname)
            self.decode_targets[rid] = cname
            return True
        # every decode-capable replica rejected (or none is healthy):
        # the ultimate fallback is the general placer over ALL healthy
        # replicas — no request is ever lost to role policy
        self.push_fallbacks += 1
        self._no_push.add(rid)
        if self.audit.enabled:
            self.audit.record(self._clock(), self.steps, "push", rid,
                              chosen=None, target=preferred,
                              fallback=True, pressures=pressures,
                              rejected=rejected)
        return self._place_rec(header, rec)

    # -- failure handling --------------------------------------------------

    def _on_replica_death(self, name: str, why: str, now: float) -> None:
        already = self.replicas[name].state is ReplicaState.DEAD
        super()._on_replica_death(name, why, now)
        if already:
            return
        # decode targets stamped onto the dead replica re-choose from
        # the survivors (the ISSUE's re-chosen-on-death contract)
        for rid, tgt in list(self.decode_targets.items()):
            if tgt == name and rid not in self.outputs:
                self._stamp_decode_target(rid,
                                          exclude=frozenset((name,)))
        self._rescue_orphans()

    def _rescue_orphans(self) -> None:
        """Close the one crash window the journal walk cannot see: a
        remote push_out LANDED (the source journaled its ``mig``
        receipts), the ack was lost, and the source died before the
        key-replay retry — the dead journal rightly skips the rid
        (receipted = handed off) but the manifest it cached died with
        the process, so after the base death path the request has NO
        owner.  Rebuild a requeue record from the submitted Request +
        the delivered stream (deterministic re-derivation: the replay
        is bit-identical by the PR 5 argument) and park it for
        placement.  Single-ownership holds — the dead journal's receipt
        already disowned the rid."""
        parked = {req.request_id for req in self._pending_reqs}
        parked |= {rec["rid"] for _, rec, _ in self._pending_recs}
        for rid in self.streams:
            if (rid in self.outputs or rid in self.placement
                    or rid in parked):
                continue
            req = self._reqs.get(rid)
            if req is None:
                continue
            from triton_dist_tpu.serve.recovery import MANIFEST_FORMAT
            header = {"format": MANIFEST_FORMAT, "clock": self._clock()}
            rec = {
                "rid": rid,
                "prompt": [int(x) for x in np.asarray(req.prompt)],
                "params": req.params.to_dict(),
                "arrival": req.arrival_time,
                "slo": req.slo_class,
                "tokens": [int(t) for t in self.streams[rid]],
                "trace": req.trace,
            }
            self.audit.record(self._clock(), self.steps, "push", rid,
                              chosen=None, orphan_rescue=True)
            self._pending_recs.append(
                (header, rec, self._rec_expiry(header, rec)))
        self._drain_pending()

    # -- observability -----------------------------------------------------

    def fleet_summary(self) -> dict:
        s = super().fleet_summary()
        s["disagg"] = {
            "prefill": self.n_prefill,
            "decode": self.n_decode,
            "pushes": self.pushes,
            "push_fallbacks": self.push_fallbacks,
        }
        return s
