"""Shared integrity primitives for every durable / wire-crossing
serving artifact (docs/serving.md "Durability & integrity").

The exactly-once story (serve/recovery.py, serve/net.py) rests on
artifacts — the token journal, snapshot manifests, migration manifests,
base64 KV blobs — whose bytes were, before this module, trusted
verbatim.  A flipped bit in any of them used to become either silent
token loss (a journal line skipped) or subtly-wrong KV (a corrupt pool
leaf adopted).  Every producer now stamps a CRC32 digest and every
reader verifies BEFORE adoption; corruption downgrades to a loud
salvage/re-queue, never wrong state.

Why CRC32: the adversary is bit rot and torn writes, not a forger —
a 32-bit checksum over the canonical JSON (or raw bytes) catches the
random-corruption class at negligible cost on the per-token journal
path (the `serve_trace_overhead`-style paired bench gate keeps it
honest).  Canonical form is ``json.dumps(..., sort_keys=True,
separators=(",", ":"))``: ``json.loads`` → ``dumps`` round-trips
deterministically in Python (shortest-repr floats, ensure_ascii), so
the digest survives a decode/re-encode even when the original byte
layout does not.

The ``durable-writes-integrity`` lint rule (analysis/rules.py) pins the
convention: every ``json.dump``/``open(..., "w")`` of a durable serving
artifact under ``serve/`` must route through :func:`atomic_write_json`
(or carry its own atomicity + digest evidence, like the journal's
framing methods).
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Optional

#: digest field name for whole-document JSON artifacts
#: (:func:`atomic_write_json` / :func:`verify_json_doc`)
DOC_CRC = "doc_crc"

#: digest field name for per-line journal records
#: (``TokenJournal.append`` / ``replay_journal`` in serve/recovery.py)
REC_CRC = "c"


def crc32_bytes(data: bytes) -> int:
    """CRC32 of raw bytes (pool leaves, wire KV blobs)."""
    return zlib.crc32(data) & 0xFFFFFFFF


def canonical_json(obj) -> str:
    """The canonical serialization digests are computed over: sorted
    keys, no whitespace — identical for an object and its
    ``json.loads(json.dumps(obj))`` round trip.  The round trip is
    ENFORCED by taking it: JSON stringifies non-string dict keys, and
    ``sort_keys`` orders ``{1: ..., 10: ..., 2: ...}`` numerically
    before the trip but lexicographically after — a digest computed on
    the raw object would never verify against the parsed-back doc
    (block-id-keyed snapshot metadata is exactly that shape)."""
    return json.dumps(json.loads(json.dumps(obj)),
                      sort_keys=True, separators=(",", ":"))


def canonical_crc(obj, *, exclude: tuple = ()) -> int:
    """CRC32 over the canonical JSON of ``obj``, minus ``exclude``
    keys (so a digest field can live inside the object it covers)."""
    if exclude and isinstance(obj, dict):
        obj = {k: v for k, v in obj.items() if k not in exclude}
    return crc32_bytes(canonical_json(obj).encode("utf-8"))


def stamp_crc(rec: dict, *, field: str = REC_CRC) -> dict:
    """Return a copy of ``rec`` carrying its own digest under
    ``field`` (the journal-record framing)."""
    out = dict(rec)
    out[field] = canonical_crc(out, exclude=(field,))
    return out


def rec_crc_ok(rec: dict, *, field: str = REC_CRC) -> Optional[bool]:
    """Tri-state record verification: ``None`` when the record carries
    no digest (pre-integrity artifact — tolerated for back-compat),
    else whether the digest matches."""
    want = rec.get(field)
    if want is None:
        return None
    return int(want) == canonical_crc(rec, exclude=(field,))


def atomic_write_json(path: str | os.PathLike, doc: dict, *,
                      digest_field: str = DOC_CRC) -> str:
    """THE durable-JSON writer for serving artifacts: stamps a
    whole-document digest, then publishes through tmp + fsync + rename
    so a crash at any instant leaves either the old file or the
    complete new one — never a torn, and never an undigested, artifact.
    (Enforced by the ``durable-writes-integrity`` lint rule.)"""
    path = os.path.abspath(os.fspath(path))
    out = stamp_crc(doc, field=digest_field)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(out, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def verify_json_doc(doc: dict, *,
                    digest_field: str = DOC_CRC) -> Optional[bool]:
    """Tri-state whole-document verification (see :func:`rec_crc_ok`);
    does not mutate ``doc``."""
    return rec_crc_ok(doc, field=digest_field)
