"""Iteration-level FCFS scheduler with a chunked-prefill token budget.

Orca-style continuous batching: scheduling decisions happen every engine
iteration, not per request — new prompts are admitted the moment a batch
slot AND enough KV blocks exist, prompt prefill is metered in chunks so a
long prompt cannot starve in-flight decode (the budget), and decode rows
retire individually.

Preemption (vLLM-style recompute): when a running request cannot extend
its KV allocation, the LATEST-admitted running request is evicted — its
blocks free immediately, its emitted tokens are kept, and it re-queues at
the FRONT of the waiting line with ``prompt + generated`` as the new
prompt (greedy recompute is deterministic, and sampled requests keep
their per-token PRNG stream, so the emission is unchanged).
"""

from __future__ import annotations

import enum
import math
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from triton_dist_tpu.serve.block_manager import BlockManager
from triton_dist_tpu.serve.metrics import RequestMetrics
from triton_dist_tpu.serve.request import Request, slo_rank


class Status(enum.Enum):
    WAITING = "waiting"    # queued, no slot/blocks yet
    PREFILL = "prefill"    # admitted, prompt streaming through chunks
    RUNNING = "running"    # in the decode batch
    FINISHED = "finished"


@dataclass
class ReqState:
    """Engine-side state of one request (the scheduler moves it between
    queues; the engine owns its device-facing fields)."""

    req: Request
    metrics: RequestMetrics
    status: Status = Status.WAITING
    slot: Optional[int] = None      # decode-batch row while admitted
    kv_len: int = 0                 # committed cache rows
    prefill_pos: int = 0            # prompt tokens already prefilled
    generated: list[int] = field(default_factory=list)
    pending_token: Optional[int] = None  # emitted, not yet consumed
    seq: int = 0                    # admission order (preemption victim)
    # recompute prompt: original prompt + tokens generated before a
    # preemption (rebuilt by the scheduler on eviction)
    work_prompt: Optional[np.ndarray] = None
    # chunked-prefill scratch (engine-owned): per-layer contiguous K/V
    # [1, Hkv, s_ext, D] the prompt streams into before the page scatter
    scratch: Optional[list] = None
    s_ext: int = 0
    # failure containment (engine-owned): a request whose on_token
    # callback raised keeps serving with the callback off (logged once)
    callback_disabled: bool = False
    # crash recovery (engine-owned): number of tokens restored from the
    # durable journal when this state was rebuilt (0 on a fresh
    # request).  Post-restore commits continue at len(generated), which
    # starts AT this index — the pre-populated `generated` list is what
    # keeps a restored stream from re-journaling or re-delivering a
    # pre-crash token; this field records that provenance and bounds
    # the restore(replay_tokens=True) redelivery
    journal_base: int = 0
    # prefix cache (docs/serving.md "Prefix caching"): tokens of this
    # admission's prompt covered by shared cached blocks (block-aligned;
    # set by admit(), reset on preemption — the re-admission re-matches).
    # The engine starts chunked prefill at the chunk floor of this, so a
    # warm prefix pays ~one residual chunk instead of the whole prompt.
    cached_prefix: int = 0
    # full logical pages whose token contents the engine has committed to
    # the content index (a watermark, monotone within one admission)
    committed_pages: int = 0
    # whether this admission attempt already counted toward the block
    # manager's lookups/lookup_hits gauges (a blocked head re-matches
    # every step; only the first walk per admission attempt counts, so
    # hit_rate stays per-request, not per-retry)
    lookup_counted: bool = False
    # memoized match_prefix result for THIS admission attempt, valid
    # while the index generation it was computed under is current — a
    # capacity-blocked head re-enters admission every engine step, and
    # without the memo each retry re-pays the O(prompt) chain walk
    match_cache: Optional[list] = None
    match_gen: int = -1
    # speculative decoding (docs/serving.md "Speculative decoding"):
    # recent (proposed, accepted) pairs, one per fused round this row
    # took part in — the windowed acceptance estimate behind the
    # scheduler's adaptive per-row k (choose_spec_k); trimmed by the
    # engine, survives preemption (acceptance is a property of the
    # request's text, not of its admission)
    spec_window: list = field(default_factory=list)
    # brownout ladder (engine-owned; docs/serving.md "Overload, SLO
    # classes & autoscaling"): a rung-3 emission cap for best-effort
    # rows — ``remaining_new`` and the LENGTH finish check both honor
    # it, while ``total_tokens`` (the admitted cache ceiling) does not,
    # so capping never re-plans allocations.  ``None`` = uncapped (the
    # default path is untouched).
    new_cap: Optional[int] = None

    def expired(self, now: float) -> bool:
        """Past its deadline TTL (``params.deadline_s`` from arrival)."""
        d = self.req.params.deadline_s
        return (d is not None and self.req.arrival_time is not None
                and now - self.req.arrival_time > d)

    @property
    def prompt_tokens(self) -> np.ndarray:
        return (self.work_prompt if self.work_prompt is not None
                else self.req.prompt)

    @property
    def effective_max_new(self) -> int:
        """``params.max_new_tokens``, clamped by a brownout ``new_cap``
        (the cap is applied with >= 1 token of headroom, so a live row
        always retires through a normal LENGTH commit)."""
        m = self.req.params.max_new_tokens
        return m if self.new_cap is None else min(m, self.new_cap)

    @property
    def remaining_new(self) -> int:
        return self.effective_max_new - len(self.generated)

    @property
    def total_tokens(self) -> int:
        """The request's admitted cache ceiling (prompt + max_new):
        invariant under preemption/recompute — the recompute prompt
        absorbs generated tokens 1:1 from the remaining budget."""
        return int(self.req.prompt.shape[0]) + self.req.params.max_new_tokens


class FCFSScheduler:
    """First-come-first-served admission + prefill metering + LIFO
    preemption, all against one :class:`BlockManager`."""

    def __init__(self, block_manager: BlockManager, *,
                 prefill_budget: int, prefill_chunk: int,
                 class_aware: bool = False):
        assert prefill_chunk >= 1 and prefill_budget >= 1
        self.bm = block_manager
        # Batch-slot capacity lives with the ENGINE (admit() is bounded
        # by the free_slots list it passes in) — one source of truth.
        # tokens of prompt prefill allowed per engine iteration; at least
        # one chunk always proceeds so prefill cannot livelock
        self.prefill_budget = prefill_budget
        self.prefill_chunk = prefill_chunk
        # SLO-class-aware policy (docs/serving.md "Overload, SLO classes
        # & autoscaling"): admission considers waiting requests in
        # (class rank, queue position) order and preemption spends the
        # worst class first.  Both orders are STABLE on arrival, so with
        # every request in one class (the default — slo_class defaults
        # to "interactive") they reduce bit-for-bit to FCFS / LIFO.
        self.class_aware = class_aware
        self.waiting: deque[ReqState] = deque()
        self._seq = 0

    # -- queue ------------------------------------------------------------

    def add(self, rs: ReqState, *, front: bool = False) -> None:
        (self.waiting.appendleft if front else self.waiting.append)(rs)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def pop_expired(self, now: float) -> list[ReqState]:
        """Drop WAITING requests whose deadline TTL has passed (the
        engine retires them with ``FinishReason.DEADLINE``).  Swept
        every iteration BEFORE admission, so an expired head of line
        frees its queue position for live requests behind it."""
        expired = [rs for rs in self.waiting if rs.expired(now)]
        for rs in expired:
            self.waiting.remove(rs)
        return expired

    # -- admission --------------------------------------------------------

    def admit(self, free_slots: list[int], now: float) -> list[ReqState]:
        """Pop waiting requests while a slot and their prompt's blocks
        (plus one decode-headroom block) are available.  FCFS: the head
        blocking keeps everyone behind it queued — no starvation.

        With the block manager's prefix cache on, the prompt's longest
        cached block-aligned prefix maps in as SHARED blocks: only the
        remainder needs free blocks (so a warm prompt admits under
        pressure a cold one could not), and ``rs.cached_prefix`` tells
        the engine where chunked prefill may start.  A recompute prompt
        (``work_prompt`` after preemption) matches the same way — the
        victim's own committed blocks usually sit in the cache tier, so
        preemption recompute collapses too.

        With ``class_aware`` on, candidates are scanned in (class rank,
        queue position) order — a stable sort, so within one class it IS
        the FCFS order, and with every request in one class the two
        paths admit identically.  Head-of-line blocking applies within
        that order: the first blocked candidate stops the scan, so no
        class starves its own members and no lower class jumps a
        blocked higher-class head."""
        admitted = []
        if self.class_aware:
            queue = sorted(self.waiting,
                           key=lambda r: slo_rank(r.req.slo_class))
        else:
            queue = list(self.waiting)
        for rs in queue:
            if not free_slots:
                break
            # Every admission needs >= 1 fresh block (match_prefix caps
            # at n_prompt - 1 tokens, so shared pages never cover the
            # prompt + headroom) — with nothing allocatable, skip the
            # O(prompt) chain walk entirely.
            if self.bm.num_free == 0:
                break
            n_prompt = int(rs.prompt_tokens.shape[0])
            # match_prefix caps at n_prompt - 1: at least one prompt
            # token always prefills (the request needs its logits).
            if (rs.match_cache is not None
                    and rs.match_gen == self.bm.index_gen):
                shared = rs.match_cache
            else:
                shared = self.bm.match_prefix(
                    np.asarray(rs.prompt_tokens),
                    count=not rs.lookup_counted)
                rs.lookup_counted = True
                rs.match_cache = shared
                rs.match_gen = self.bm.index_gen
            # +1 token of headroom: admission must leave room to decode
            # at least one token past the prompt, or the request would
            # immediately preempt something.
            if not self.bm.can_allocate(n_prompt + 1, shared):
                break
            self.waiting.remove(rs)
            rs.slot = free_slots.pop(0)
            rs.status = Status.PREFILL
            rs.prefill_pos = 0
            rs.kv_len = 0
            rs.seq = self._seq
            self._seq += 1
            self.bm.allocate(rs.req.request_id, n_prompt + 1,
                             shared=shared)
            rs.match_cache = None  # consumed
            rs.cached_prefix = len(shared) * self.bm.page_size
            rs.committed_pages = len(shared)
            rs.metrics.on_scheduled(now)
            admitted.append(rs)
        return admitted

    # -- chunked-prefill metering ----------------------------------------

    def prefill_plan(self, prefilling: list[ReqState]) -> list[tuple]:
        """Assign this iteration's prompt-token budget to PREFILL-state
        requests (admission order).  Returns [(rs, n_tokens)]; the first
        assignment always gets at least one chunk (progress guarantee).

        Assignments are quantized to WHOLE ``prefill_chunk`` multiples
        (except a prompt's final residual, which the engine pads up to a
        full chunk): every ``_chunk_jit`` call then has the one fixed
        chunk shape, so prefill never retraces on prompt length — the
        trace-cache contract of docs/serving.md's bucket ladder.  A
        padded final chunk is charged as a full chunk of budget (it
        costs a full chunk of compute)."""
        plan = []
        budget = self.prefill_budget
        chunk = self.prefill_chunk
        for rs in sorted(prefilling, key=lambda r: r.seq):
            remaining = int(rs.prompt_tokens.shape[0]) - rs.prefill_pos
            if remaining <= 0:
                continue
            if not plan:
                # Head of line: at least one chunk even when budget <
                # chunk (otherwise a budget smaller than the chunk size
                # would stall prefill forever).
                n_chunks = max(1, budget // chunk)
            elif budget < chunk:
                break
            else:
                n_chunks = budget // chunk
            n_chunks = min(n_chunks, -(-remaining // chunk))
            plan.append((rs, min(remaining, n_chunks * chunk)))
            budget -= n_chunks * chunk
        return plan

    # -- decode-horizon planning -----------------------------------------

    def plan_horizon(self, horizon: int, *, prefilling: bool, spec: bool,
                     deadline_waiting: bool) -> int:
        """Decode steps ONE device dispatch may fuse this iteration (the
        engine buckets the result down its horizon ladder and enforces
        per-row budgets on device — docs/serving.md "Decode horizon").

        Fusing trades scheduling granularity for dispatch economy, so the
        plan clamps back to ITERATION-LEVEL decode (1) whenever a fused
        horizon would break a per-step contract:

        - ``spec``: speculative rounds are already multi-token per
          dispatch and share device state across rows; they keep their
          own round machinery (this also keeps a post-bailout engine on
          the warmed single-step program).
        - ``prefilling``: mid-prefill rows are owed chunk budget every
          iteration — a fused horizon would freeze their TTFT for its
          whole duration.
        - ``deadline_waiting``: WAITING deadlines are swept at step
          boundaries; fusing would delay the sweep (and the blocks it
          frees) by the horizon's wall time.

        A non-empty waiting queue WITHOUT deadlines does not clamp:
        admission runs before decode each step, so anything still queued
        at decode time could not be admitted now anyway, and retirements
        that unblock it only land at the horizon's drain regardless."""
        if horizon <= 1 or spec or prefilling or deadline_waiting:
            return 1
        return horizon

    # -- speculative planning --------------------------------------------

    def plan_spec(self, pipeline: int, *, prefilling: bool,
                  deadline_waiting: bool) -> int:
        """Fused speculative rounds ONE engine step may chain on a
        device-resident carry (the spec twin of :meth:`plan_horizon` —
        a chained round is a spec-shaped horizon link).  The same
        per-step contracts clamp chaining back to one round per step:
        mid-prefill rows are owed chunk budget every iteration, and
        WAITING deadlines are swept at step boundaries.  The
        ``plan_horizon`` spec clamp does NOT apply here — a spec round
        is already the multi-token dispatch it protects."""
        if pipeline <= 1 or prefilling or deadline_waiting:
            return 1
        return pipeline

    def choose_spec_k(self, rs: ReqState, k_max: int, *, window: int = 8,
                      floor: float = 0.25) -> int:
        """Per-row speculation depth from a windowed acceptance-rate
        estimate: under an i.i.d.-acceptance model with per-token rate
        ``alpha`` (the window's accepted/proposed), a k-token chain
        fully accepts with probability ``alpha ** k`` — pick the
        deepest k that still clears ``floor``, so a well-matched draft
        speculates the full ``k_max`` while a mismatched one collapses
        to 1 instead of burning k draft steps per emitted token.
        Optimistic while the window is still filling (a fresh request
        starts at full depth); the evidence floor is min(k_max, window)
        proposals so a COLLAPSED row — whose window holds `window`
        1-proposal rounds, fewer than k_max proposals — stays collapsed
        instead of periodically resetting to full depth (and dragging
        the whole batch's k-rung up with it).  The engine buckets the
        batch max down the pow2 k-ladder, so the chosen depths never
        cost fresh traces."""
        window = max(window, 1)
        hist = rs.spec_window[-window:]
        prop = sum(p for p, _ in hist)
        if k_max <= 1 or prop < min(k_max, window):
            return max(k_max, 1)
        alpha = sum(a for _, a in hist) / prop
        if alpha <= 0.0:
            return 1
        if alpha >= 1.0:
            return k_max
        return max(1, min(k_max, int(math.log(floor) / math.log(alpha))))

    # -- preemption -------------------------------------------------------

    def pick_victim(self, running: list[ReqState],
                    needy: ReqState) -> Optional[ReqState]:
        """LIFO eviction: the latest-admitted running request other than
        ``needy`` (evicting the one that still needs blocks would free
        nothing it can use — its own blocks come back to it).

        With ``class_aware`` on, the worst SLO class is spent first —
        best-effort before batch before interactive — LIFO within a
        class.  With every request in one class the (rank, seq) max is
        the seq max, so the default path is unchanged."""
        candidates = [r for r in running if r is not needy]
        if not candidates:
            return None
        if self.class_aware:
            return max(candidates,
                       key=lambda r: (slo_rank(r.req.slo_class), r.seq))
        return max(candidates, key=lambda r: r.seq)

    def pick_shed_victim(self, rank: int) -> Optional[ReqState]:
        """Class-aware overload displacement: the latest-queued WAITING
        request of the WORST class strictly below service rank ``rank``
        (higher ``slo_rank``), or ``None`` when no lower class holds a
        queue slot.  Used by the engine when the waiting queue is at
        ``max_queue``: an arriving higher-class request sheds this
        victim and takes its slot instead of being refused — interactive
        is never shed while best-effort or batch occupies the queue."""
        worst: Optional[ReqState] = None
        worst_key = (rank, -1)
        for i, rs in enumerate(self.waiting):
            key = (slo_rank(rs.req.slo_class), i)
            if key > worst_key:
                worst, worst_key = rs, key
        return worst

    def preempt(self, rs: ReqState) -> None:
        """Evict ``rs``: free its blocks and re-queue it (front) for
        recompute — the new prompt is everything already committed, so
        emitted tokens stay emitted."""
        self.bm.free(rs.req.request_id)
        rs.work_prompt = np.concatenate(
            [rs.req.prompt, np.asarray(rs.generated, np.int32)])
        rs.status = Status.WAITING
        rs.slot = None
        rs.kv_len = 0
        rs.prefill_pos = 0
        rs.pending_token = None
        rs.cached_prefix = 0
        rs.committed_pages = 0
        # The recompute admission re-matches (and may land cold): a
        # request whose TTFT is still pending must be re-classified by
        # what that admission finds, not by the one that was evicted.
        # An already-recorded TTFT keeps its warm/cold label.
        rs.lookup_counted = False
        rs.match_cache = None  # the recompute prompt is different
        rs.match_gen = -1
        if rs.metrics.first_token_time is None:
            rs.metrics.cached_prefix_tokens = 0
        rs.metrics.n_preemptions += 1
        self.add(rs, front=True)
