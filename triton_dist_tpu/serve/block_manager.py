"""Paged-KV block allocator: free list + per-request block tables.

The physical cache is a pool of ``num_blocks`` pages of ``page_size``
token rows each (per layer, per K/V — the pools live in the engine; this
class owns only the *index* arithmetic, so it is trivially unit-testable
and the engine's device arrays follow it).

Block 0 is RESERVED as the null block: retired/inactive batch rows
redirect their dummy K/V writes there, and dead block-table entries
(logical pages past a request's allocation) point at it — so a pool row
freed and re-allocated to another request can never be corrupted by a
stale writer, and every table entry always indexes a valid pool row (the
paged kernel DMAs dead entries too; see kernels/flash_decode.py).

Contract with `kernels/flash_decode.gqa_decode_paged_shard`: logical page
``i`` of a request lives at pool row ``table(rid)[i]``; entries past the
allocation hold the null block and are masked by the sequence length.
"""

from __future__ import annotations


class BlockExhausted(Exception):
    """Raised by :meth:`BlockManager.allocate` /
    :meth:`BlockManager.ensure` when the free list cannot cover the
    request (the scheduler turns this into queueing or preemption)."""


class BlockManager:
    def __init__(self, num_blocks: int, page_size: int, *, faults=None):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {num_blocks}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_blocks = num_blocks
        self.page_size = page_size
        self.null_block = 0
        # runtime.faults.FaultInjector (optional): the mid-grow alloc is
        # a fault point — an injected failure exercises the engine's
        # quarantine path without a genuinely exhausted pool.
        self._faults = faults
        # LIFO free list: recently-freed (cache-warm) blocks are reused
        # first.  Block 0 never enters it.
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._tables: dict[str, list[int]] = {}

    # -- accounting -------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocatable(self) -> int:
        return self.num_blocks - 1

    @property
    def utilization(self) -> float:
        """Fraction of allocatable blocks currently held by requests."""
        used = self.num_allocatable - self.num_free
        return used / self.num_allocatable

    def blocks_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` cache rows."""
        return -(-n_tokens // self.page_size)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for(n_tokens) <= self.num_free

    # -- allocate / extend / free ----------------------------------------

    def allocate(self, rid: str, n_tokens: int) -> list[int]:
        """Allocate blocks covering ``n_tokens`` for a NEW request."""
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already has blocks")
        need = self.blocks_for(n_tokens)
        if need > self.num_free:
            raise BlockExhausted(
                f"{rid}: need {need} blocks for {n_tokens} tokens, "
                f"only {self.num_free} free")
        self._tables[rid] = [self._free.pop() for _ in range(need)]
        return list(self._tables[rid])

    def ensure(self, rid: str, n_tokens: int) -> list[int]:
        """Extend ``rid``'s allocation to cover ``n_tokens`` (no-op when
        it already does).  Returns the blocks appended."""
        table = self._tables[rid]
        need = self.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return []
        if self._faults is not None:
            # Fires BEFORE the free list is touched: an injected alloc
            # failure (InjectedFault, not BlockExhausted) leaves the pool
            # intact and bypasses the preemption machinery, so it lands
            # on the engine's quarantine path.
            self._faults.fire("block_alloc", rid=rid)
        if need > self.num_free:
            raise BlockExhausted(
                f"{rid}: extension to {n_tokens} tokens needs {need} more "
                f"blocks, only {self.num_free} free")
        fresh = [self._free.pop() for _ in range(need)]
        table.extend(fresh)
        return fresh

    def adopt(self, rid: str, blocks: list[int]) -> None:
        """Impose a block table restored from a snapshot: claim exactly
        ``blocks`` (in order) for ``rid``, removing them from the free
        list.  The restore-time twin of :meth:`allocate` — the snapshot
        already decided WHICH physical pages hold the request's KV, so
        the allocator must adopt that mapping rather than hand out fresh
        pages the restored pools never wrote."""
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already has blocks")
        blocks = [int(b) for b in blocks]
        bad = [b for b in blocks
               if b == self.null_block or not 0 < b < self.num_blocks]
        if bad:
            raise ValueError(f"{rid}: cannot adopt blocks {bad} "
                             f"(null or outside pool {self.num_blocks})")
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"{rid}: duplicate blocks in {blocks}")
        missing = set(blocks) - set(self._free)
        if missing:
            raise ValueError(
                f"{rid}: blocks {sorted(missing)} already owned — the "
                f"snapshot tables overlap")
        taken = set(blocks)
        self._free = [b for b in self._free if b not in taken]
        self._tables[rid] = blocks

    def free(self, rid: str) -> None:
        """Return all of ``rid``'s blocks to the free list."""
        for b in reversed(self._tables.pop(rid)):
            self._free.append(b)

    # -- tables -----------------------------------------------------------

    def table(self, rid: str) -> list[int]:
        return list(self._tables[rid])

    def padded_table(self, rid: str, width: int) -> list[int]:
        """The request's block table padded to ``width`` logical pages
        with the null block (the engine's fixed-width device row)."""
        t = self._tables[rid]
        if len(t) > width:
            raise ValueError(
                f"{rid}: {len(t)} blocks exceed table width {width}")
        return t + [self.null_block] * (width - len(t))

    def capacity_tokens(self, rid: str) -> int:
        """Cache rows the request's current allocation can hold."""
        return len(self._tables[rid]) * self.page_size
