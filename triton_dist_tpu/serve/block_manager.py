"""Paged-KV block allocator: free list, per-request block tables, and a
content-addressed prefix cache with copy-on-write sharing.

The physical cache is a pool of ``num_blocks`` pages of ``page_size``
token rows each (per layer, per K/V — the pools live in the engine; this
class owns only the *index* arithmetic, so it is trivially unit-testable
and the engine's device arrays follow it).

Block 0 is RESERVED as the null block: retired/inactive batch rows
redirect their dummy K/V writes there, and dead block-table entries
(logical pages past a request's allocation) point at it — so a pool row
freed and re-allocated to another request can never be corrupted by a
stale writer, and every table entry always indexes a valid pool row (the
paged kernel DMAs dead entries too; see kernels/flash_decode.py).

Contract with `kernels/flash_decode.gqa_decode_paged_shard`: logical page
``i`` of a request lives at pool row ``table(rid)[i]``; entries past the
allocation hold the null block and are masked by the sequence length.

**Prefix sharing (docs/serving.md "Prefix caching").**  Every block is
ref-counted, and a FULL block whose token contents are known can be
*committed* to a content-addressed index keyed by ``(parent block,
token ids in block)`` — the parent link makes the key a chain, so a hit
at logical page ``i`` certifies the ENTIRE prefix up to ``i``, not just
this page's tokens at some position.  ``match_prefix`` walks the chain
to find the longest cached block-aligned prefix of a prompt, and
``allocate(..., shared=...)`` maps those blocks read-only into a new
request's table (refcount++).  Writes into a block with refcount > 1 go
through :meth:`cow` first (copy-on-write — the caller copies the page
on device and the table entry swaps to the fresh block).  Freed blocks
whose contents are committed don't die: they enter an LRU-evictable
cache tier, reclaimed only under allocation pressure — so
``num_free``/``num_allocatable`` semantics (and the ``BlockExhausted``
→ preemption path above them) are unchanged, the cache just keeps warm
KV alive in pages nobody is using yet.

Hash-collision safety: the index buckets on :func:`_block_hash` but a
lookup only matches after a FULL ``(parent, token ids)`` compare — a
colliding hash can never alias two different prefixes (pinned by
tests/test_serve_prefix.py with a deliberately degenerate hash).
"""

from __future__ import annotations

from typing import Optional, Sequence

_ROOT = 0  # parent sentinel for a request's first block (the null block
           # can never be committed, so the id is free to mean "no parent")


def _block_hash(parent: int, tokens: tuple) -> int:
    """Bucket key for the content index.  Collisions are SAFE (lookup
    compares the full (parent, tokens) pair) — tests monkeypatch this to
    a constant to prove it."""
    return hash((parent, tokens))


class BlockExhausted(Exception):
    """Raised by :meth:`BlockManager.allocate` /
    :meth:`BlockManager.ensure` when the free list plus the evictable
    cache tier cannot cover the request (the scheduler turns this into
    queueing or preemption)."""


class BlockManager:
    def __init__(self, num_blocks: int, page_size: int, *, faults=None,
                 prefix_cache: bool = False, shards: int = 1,
                 pages_per_shard: Optional[int] = None):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {num_blocks}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        # Sequence-sharded serving (docs/serving.md "Sharded serving"):
        # with shards=W the block-id space splits into W equal
        # partitions — rank r's pool holds global blocks
        # [r*NB/W, (r+1)*NB/W) — and logical page ``i`` of ANY request
        # must be allocated from partition ``i // pages_per_shard``
        # (contiguous sequence-span ownership, the
        # sp_gqa_decode_paged_shard contract).  Each partition reserves
        # its own null block (its first id): per-rank dummy writes
        # redirect to LOCAL row 0, so one global null cannot serve
        # every rank.  shards=1 is the world-1 engine, bit-identical to
        # the pre-mesh allocator.
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        if num_blocks % shards:
            raise ValueError(f"num_blocks {num_blocks} must divide by "
                             f"shards {shards}")
        if shards > 1 and num_blocks // shards < 2:
            raise ValueError(
                f"num_blocks//shards = {num_blocks // shards}: every "
                f"partition reserves a null block and still needs an "
                f"allocatable page")
        if shards > 1 and not pages_per_shard:
            raise ValueError("shards > 1 needs pages_per_shard (the "
                             "logical-page span each rank owns)")
        self.num_blocks = num_blocks
        self.page_size = page_size
        self.shards = shards
        self.pages_per_shard = pages_per_shard or num_blocks
        self._nb_loc = num_blocks // shards
        self.null_block = 0
        self._nulls = frozenset(r * self._nb_loc for r in range(shards))
        self.prefix_cache = bool(prefix_cache)
        # runtime.faults.FaultInjector (optional): the mid-grow alloc is
        # a fault point — an injected failure exercises the engine's
        # quarantine path without a genuinely exhausted pool.
        self._faults = faults
        # LIFO free list: recently-freed (cache-warm) blocks are reused
        # first.  Null blocks (block 0; one per partition when sharded)
        # never enter it.
        self._free: list[int] = [b for b in range(num_blocks - 1, 0, -1)
                                 if b not in self._nulls]
        self._tables: dict[str, list[int]] = {}
        # -- sharing / content cache state --------------------------------
        self._ref: dict[int, int] = {}          # block -> refcount (> 0)
        # committed blocks: block -> (parent block, token-id tuple);
        # present while the block is live-shared OR in the cache tier
        self._meta: dict[int, tuple[int, tuple]] = {}
        self._index: dict[int, list[int]] = {}  # _block_hash -> blocks
        self._children: dict[int, set[int]] = {}
        # LRU cache tier: committed refcount-0 blocks, insertion-ordered
        # (dict iteration order = admission order = eviction order)
        self._cached: dict[int, None] = {}
        # observability (engine surfaces these via metrics.summary())
        # on_evict(block): optional hook fired as a cache-tier block is
        # reclaimed — the engine points it at the flight recorder so
        # eviction storms land on the request timeline
        # (docs/observability.md); must never raise (called on the
        # allocation hot path).
        self.on_evict = None
        self.lookups = 0          # match_prefix calls
        self.lookup_hits = 0      # match_prefix calls matching > 0 blocks
        self.hit_blocks = 0       # blocks mapped read-only into tables
        self.committed_blocks = 0  # commit_block registrations
        self.cow_copies = 0       # copy-on-write block splits
        self.evictions = 0        # cache-tier blocks reclaimed
        # bumped on every index mutation (_register/_unregister): a
        # match_prefix result is valid for exactly as long as this is
        # unchanged, so a blocked head-of-line request can reuse its
        # match instead of re-walking the chain every engine step
        self.index_gen = 0

    # -- accounting -------------------------------------------------------

    @property
    def num_free(self) -> int:
        """Blocks an allocation can claim: the free list PLUS the
        evictable cache tier (cached blocks hold warm KV but belong to
        nobody — allocation pressure reclaims them LRU-first)."""
        return len(self._free) + len(self._cached)

    @property
    def num_cached(self) -> int:
        """Blocks in the evictable warm-KV cache tier (refcount 0)."""
        return len(self._cached)

    @property
    def num_shared(self) -> int:
        """Blocks currently mapped into more than one table."""
        return sum(1 for r in self._ref.values() if r > 1)

    @property
    def num_allocatable(self) -> int:
        return self.num_blocks - self.shards

    # -- partition arithmetic (shards > 1: kv_shard="seq") ---------------

    def part_of_block(self, block: int) -> int:
        """Partition owning physical block ``block``."""
        return block // self._nb_loc

    def part_of_page(self, logical: int) -> int:
        """Partition that must hold logical page ``logical`` of any
        request (contiguous sequence-span ownership)."""
        return min(logical // self.pages_per_shard, self.shards - 1)

    def placement_ok(self, blocks: Sequence[int]) -> bool:
        """True when a position-ordered block table satisfies the
        partition constraint (trivially true unsharded).  The restore
        path gates in-place adoption on this — a table snapshotted
        under a different mesh shape re-queues through exact recompute
        instead of serving junk pages."""
        if self.shards == 1:
            return True
        return all(self.part_of_block(b) == self.part_of_page(i)
                   and b not in self._nulls
                   for i, b in enumerate(blocks))

    def _part_free(self, part: int, *, skip_cached: int = 0) -> int:
        """Free + evictable blocks available in one partition."""
        lo, hi = part * self._nb_loc, (part + 1) * self._nb_loc
        return (sum(1 for b in self._free if lo <= b < hi)
                + sum(1 for b in self._cached if lo <= b < hi)
                - skip_cached)

    def fit_error(self, n_tokens: int) -> Optional[str]:
        """Can ``n_tokens`` EVER fit this pool (all blocks free)?
        Returns None when yes, else the rejection message — per
        partition when sharded: a long request needs its span's pages
        in specific partitions, so a global block count is not enough."""
        need = self.blocks_for(n_tokens)
        if need > self.num_allocatable:
            return (f"needs {need} blocks, pool has "
                    f"{self.num_allocatable}")
        if self.shards > 1:
            for p in range(self.shards):
                in_p = sum(1 for i in range(need)
                           if self.part_of_page(i) == p)
                if in_p > self._nb_loc - 1:
                    return (f"needs {in_p} blocks in partition {p} "
                            f"(kv_shard='seq' sequence-span "
                            f"ownership), partition holds "
                            f"{self._nb_loc - 1}")
        return None

    @property
    def utilization(self) -> float:
        """Fraction of allocatable blocks currently held by requests."""
        used = self.num_allocatable - self.num_free
        return used / self.num_allocatable

    def blocks_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` cache rows."""
        return -(-n_tokens // self.page_size)

    def can_allocate(self, n_tokens: int,
                     shared: Sequence[int] = ()) -> bool:
        """Would :meth:`allocate` succeed?  ``shared`` is the
        :meth:`match_prefix` hit the allocation will map in: those
        blocks don't need the free list — but the ones currently
        sitting in the cache tier must NOT also be counted as
        evictable supply (they're about to be claimed), so they are
        subtracted from both sides."""
        in_cache = sum(1 for b in shared if b in self._cached)
        avail = len(self._free) + len(self._cached) - in_cache
        if self.blocks_for(n_tokens) - len(shared) > avail:
            return False
        if self.shards > 1:
            need = self.blocks_for(n_tokens)
            for p in range(self.shards):
                need_p = sum(1 for i in range(len(shared), need)
                             if self.part_of_page(i) == p)
                skip = sum(1 for b in shared if b in self._cached
                           and self.part_of_block(b) == p)
                if need_p > self._part_free(p, skip_cached=skip):
                    return False
        return True

    def ref_of(self, block: int) -> int:
        return self._ref.get(block, 0)

    def block_key(self, block: int) -> Optional[tuple]:
        """The content-index key ``(parent block, token ids)`` of a
        committed block, or ``None``.  The engine's draft-side prefix
        cache tags its draft pool pages with this key and re-validates
        the tag at read time — a freed-and-reused block's key changes or
        vanishes, so a stale draft page can never be served (the
        draft-pool twin of the chain's id-reuse safety)."""
        return self._meta.get(block)

    def prefix_stats(self) -> dict:
        """The prefix-cache counters + gauges as one dict (the engine's
        ``metrics.summary()["prefix_cache"]``)."""
        return {
            "lookups": self.lookups,
            "lookup_hits": self.lookup_hits,
            "hit_rate": (self.lookup_hits / self.lookups
                         if self.lookups else 0.0),
            "hit_blocks": self.hit_blocks,
            "hit_tokens": self.hit_blocks * self.page_size,
            "committed_blocks": self.committed_blocks,
            "cow_copies": self.cow_copies,
            "evictions": self.evictions,
            "cached_blocks": self.num_cached,
            "shared_blocks": self.num_shared,
        }

    # -- the content-addressed index --------------------------------------

    def _find(self, parent: int, tokens: tuple) -> Optional[int]:
        """Committed block for (parent, tokens) — FULL compare, never the
        hash alone (collision safety)."""
        for b in self._index.get(_block_hash(parent, tokens), ()):
            if self._meta.get(b) == (parent, tokens):
                return b
        return None

    def _register(self, block: int, parent: int, tokens: tuple) -> bool:
        """Enter ``block`` into the content index (idempotent; refuses a
        duplicate (parent, tokens) key — first committer wins)."""
        if block in self._meta:
            return True
        if self._find(parent, tokens) is not None:
            return False  # identical content already cached elsewhere
        self._meta[block] = (parent, tokens)
        self._index.setdefault(_block_hash(parent, tokens), []).append(block)
        if parent != _ROOT:
            self._children.setdefault(parent, set()).add(block)
        self.committed_blocks += 1
        self.index_gen += 1
        return True

    def _unregister(self, block: int) -> None:
        self.index_gen += 1
        parent, tokens = self._meta.pop(block)
        h = _block_hash(parent, tokens)
        bucket = self._index.get(h)
        if bucket is not None:
            bucket.remove(block)
            if not bucket:
                del self._index[h]
        if parent != _ROOT:
            kids = self._children.get(parent)
            if kids is not None:
                kids.discard(block)
                if not kids:
                    del self._children[parent]

    def match_prefix(self, tokens: Sequence[int], *,
                     count: bool = True) -> list[int]:
        """Longest cached block-aligned prefix of ``tokens``: the chain
        of committed blocks matching full pages of the prompt, capped at
        ``len(tokens) - 1`` so at least one token always prefills (the
        request needs the last prompt token's logits).  Returns the
        physical blocks, in logical order — pass them to
        :meth:`allocate`'s ``shared=``.

        ``count=False`` leaves the ``lookups``/``lookup_hits`` gauges
        alone: a blocked head-of-line request re-matches every engine
        step until it admits, and counting each retry would deflate
        ``hit_rate`` into a queue-pressure artifact."""
        if not self.prefix_cache or len(tokens) < 2:
            return []
        if count:
            self.lookups += 1
        page = self.page_size
        limit = (len(tokens) - 1) // page
        out: list[int] = []
        parent = _ROOT
        for i in range(limit):
            key = tuple(int(t) for t in tokens[i * page:(i + 1) * page])
            blk = self._find(parent, key)
            if blk is None:
                break
            if (self.shards > 1
                    and self.part_of_block(blk) != self.part_of_page(i)):
                # Sharded pools: a cached block is only usable at the
                # logical position whose partition physically holds it
                # (re-admitted warm blocks from a different mesh shape
                # land here and simply never match).
                break
            out.append(blk)
            parent = blk
        if out and count:
            self.lookup_hits += 1
        return out

    def commit_block(self, rid: str, logical: int,
                     tokens: Sequence[int]) -> None:
        """Register ``rid``'s full logical page ``logical`` (its
        ``page_size`` token ids are ``tokens``) in the content index so
        later prompts sharing the prefix can map it read-only.  The
        parent link is the table's previous entry — by induction the
        whole chain up to this page is certified by the commit.
        Idempotent; a no-op when the cache is disabled or identical
        content is already indexed under another block."""
        if not self.prefix_cache:
            return
        if len(tokens) != self.page_size:
            raise ValueError(
                f"{rid}: commit_block needs exactly page_size="
                f"{self.page_size} tokens, got {len(tokens)}")
        table = self._tables[rid]
        block = table[logical]
        parent = table[logical - 1] if logical > 0 else _ROOT
        self._register(block, parent,
                       tuple(int(t) for t in tokens))

    # -- allocate / extend / free ----------------------------------------

    def _pop_free(self, part: Optional[int] = None) -> int:
        """One writable block off the free list, evicting the LRU cached
        block (plus its now-unreachable cached descendants — a committed
        child whose parent is gone can never be matched again, and its
        stale chain link must not survive the parent id's reuse) when
        the list is empty.  ``part`` (sharded pools) restricts the pop
        — and any eviction — to one partition."""
        if part is None or self.shards == 1:
            if not self._free:
                if not self._cached:
                    raise BlockExhausted("no free or evictable blocks")
                self._evict(next(iter(self._cached)))
            return self._free.pop()
        lo, hi = part * self._nb_loc, (part + 1) * self._nb_loc
        for i in range(len(self._free) - 1, -1, -1):
            if lo <= self._free[i] < hi:
                return self._free.pop(i)
        victim = next((b for b in self._cached if lo <= b < hi), None)
        if victim is None:
            raise BlockExhausted(
                f"no free or evictable blocks in partition {part}")
        self._evict(victim)
        for i in range(len(self._free) - 1, -1, -1):
            if lo <= self._free[i] < hi:
                return self._free.pop(i)
        raise BlockExhausted(       # pragma: no cover — _evict freed one
            f"no free or evictable blocks in partition {part}")

    def _evict(self, block: int) -> None:
        """Reclaim a cache-tier block into the free list.  Its committed
        descendants are orphaned first: the block's id is about to be
        reusable with different contents, and a child keyed on it could
        otherwise falsely certify its chain once the id comes back."""
        if block not in self._cached:
            return
        del self._cached[block]
        self._unregister(block)
        self._orphan_children(block)
        self._free.append(block)
        self.evictions += 1
        if self.on_evict is not None:
            self.on_evict(block)

    def _orphan_children(self, block: int) -> None:
        """``block`` is returning to the free list: its id can be
        reallocated with different contents, so no committed child keyed
        on it may survive — a match walking through the REUSED id would
        certify a chain the child's KV was never computed under (the
        block-id-reuse twin of hash-collision safety).  Cached children
        are reclaimed outright; live-shared children only lose their
        index entry (their holders' KV stays valid, the chain is just no
        longer matchable — their own children stay registered and are
        orphaned in turn when the live child is eventually freed)."""
        for child in list(self._children.get(block, ())):
            if child in self._cached:
                self._evict(child)
            else:
                self._unregister(child)

    def _claim_shared(self, block: int) -> None:
        """Map an existing block into one more table: refcount++ (pulling
        it out of the cache tier when it sat at refcount 0)."""
        if block in self._cached:
            del self._cached[block]
        self._ref[block] = self._ref.get(block, 0) + 1

    def allocate(self, rid: str, n_tokens: int,
                 shared: Sequence[int] = ()) -> list[int]:
        """Allocate blocks covering ``n_tokens`` for a NEW request.
        ``shared`` (from :meth:`match_prefix`) maps those blocks
        read-only as the table's head — only the remainder comes off the
        free list."""
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already has blocks")
        need = self.blocks_for(n_tokens)
        shared = list(shared)
        if len(shared) > need:
            raise ValueError(
                f"{rid}: {len(shared)} shared blocks exceed the "
                f"{need}-block allocation for {n_tokens} tokens")
        # Same availability math as can_allocate: shared blocks sitting
        # in the cache tier are about to be CLAIMED, so they cannot also
        # count as evictable supply for the fresh remainder.
        avail = self.num_free - sum(1 for b in shared if b in self._cached)
        if need - len(shared) > avail:
            raise BlockExhausted(
                f"{rid}: need {need - len(shared)} blocks for {n_tokens} "
                f"tokens ({len(shared)} shared), only {avail} free")
        if self.shards > 1:
            # Partitioned placement: every fresh page must come from its
            # logical position's partition, and the availability check
            # must hold PER PARTITION (the global count above can pass
            # while the one partition this span needs is empty).
            if not self.placement_ok(shared):
                raise ValueError(
                    f"{rid}: shared prefix blocks {list(shared)} violate "
                    f"the partition placement (kv_shard='seq')")
            for p in range(self.shards):
                need_p = sum(1 for i in range(len(shared), need)
                             if self.part_of_page(i) == p)
                skip = sum(1 for b in shared if b in self._cached
                           and self.part_of_block(b) == p)
                if need_p > self._part_free(p, skip_cached=skip):
                    raise BlockExhausted(
                        f"{rid}: need {need_p} blocks in partition {p} "
                        f"for {n_tokens} tokens, only "
                        f"{self._part_free(p, skip_cached=skip)} free")
        table = []
        for b in shared:
            self._claim_shared(b)
            table.append(b)
        for i in range(len(shared), need):
            b = self._pop_free(self.part_of_page(i)
                               if self.shards > 1 else None)
            self._ref[b] = 1
            table.append(b)
        self._tables[rid] = table
        self.hit_blocks += len(shared)
        return list(table)

    def ensure(self, rid: str, n_tokens: int) -> list[int]:
        """Extend ``rid``'s allocation to cover ``n_tokens`` (no-op when
        it already does).  Returns the blocks appended."""
        table = self._tables[rid]
        need = self.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return []
        if self._faults is not None:
            # Fires BEFORE the free list is touched: an injected alloc
            # failure (InjectedFault, not BlockExhausted) leaves the pool
            # intact and bypasses the preemption machinery, so it lands
            # on the engine's quarantine path.
            self._faults.fire("block_alloc", rid=rid)
        if need > self.num_free:
            raise BlockExhausted(
                f"{rid}: extension to {n_tokens} tokens needs {need} more "
                f"blocks, only {self.num_free} free")
        base = len(table)
        if self.shards > 1:
            for p in range(self.shards):
                need_p = sum(1 for i in range(base, base + need)
                             if self.part_of_page(i) == p)
                if need_p > self._part_free(p):
                    raise BlockExhausted(
                        f"{rid}: extension to {n_tokens} tokens needs "
                        f"{need_p} blocks in partition {p}, only "
                        f"{self._part_free(p)} free")
        fresh = []
        for i in range(base, base + need):
            b = self._pop_free(self.part_of_page(i)
                               if self.shards > 1 else None)
            self._ref[b] = 1
            fresh.append(b)
        table.extend(fresh)
        return fresh

    def cow(self, rid: str, logical: int) -> tuple[int, int]:
        """Copy-on-write split of ``rid``'s logical page ``logical``: the
        shared block's refcount drops, a fresh block takes its table
        slot, and ``(old, new)`` returns so the caller can copy the page
        on device BEFORE any write lands.  Raises ``BlockExhausted``
        when no block (free or evictable) remains."""
        table = self._tables[rid]
        old = table[logical]
        if self._ref.get(old, 0) <= 1:
            raise ValueError(
                f"{rid}: block {old} (logical {logical}) is not shared")
        # The split stays in the logical page's partition (sharded
        # pools): the device copy is rank-local by construction.
        new = self._pop_free(self.part_of_page(logical)
                             if self.shards > 1 else None)
        self._ref[old] -= 1
        self._ref[new] = 1
        table[logical] = new
        self.cow_copies += 1
        return old, new

    def share(self, rid: str, blocks: Sequence[int]) -> None:
        """Impose a table for ``rid`` that references ``blocks`` in
        order, sharing any block another table already owns
        (refcount++), claiming cache-tier blocks, and taking free-list
        blocks.  The sharing twin of :meth:`adopt` — beam search maps
        every beam onto one prefix this way, and restore rebuilds
        snapshot tables that legitimately overlap."""
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already has blocks")
        blocks = [int(b) for b in blocks]
        bad = [b for b in blocks
               if b in self._nulls or not 0 <= b < self.num_blocks]
        if bad:
            raise ValueError(f"{rid}: cannot claim blocks {bad} "
                             f"(null or outside pool {self.num_blocks})")
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"{rid}: duplicate blocks in {blocks}")
        if not self.placement_ok(blocks):
            raise ValueError(
                f"{rid}: blocks {blocks} violate the partition "
                f"placement (kv_shard='seq': logical page i lives in "
                f"partition i // {self.pages_per_shard})")
        free = set(self._free)
        for b in blocks:
            if b in free:
                self._free.remove(b)
                free.discard(b)
                self._ref[b] = 1
            else:
                self._claim_shared(b)
        self._tables[rid] = blocks

    def adopt(self, rid: str, blocks: list[int], *,
              shared_ok: bool = False) -> None:
        """Impose a block table restored from a snapshot: claim exactly
        ``blocks`` (in order) for ``rid``, removing them from the free
        list.  The restore-time twin of :meth:`allocate` — the snapshot
        already decided WHICH physical pages hold the request's KV, so
        the allocator must adopt that mapping rather than hand out fresh
        pages the restored pools never wrote.  ``shared_ok=True`` lets
        blocks another restored table already claimed ride along as
        shared (refcount++) — snapshot tables legitimately overlap when
        the snapshotted engine served a shared prefix."""
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already has blocks")
        blocks = [int(b) for b in blocks]
        bad = [b for b in blocks
               if b in self._nulls or not 0 <= b < self.num_blocks]
        if bad:
            raise ValueError(f"{rid}: cannot adopt blocks {bad} "
                             f"(null or outside pool {self.num_blocks})")
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"{rid}: duplicate blocks in {blocks}")
        if not shared_ok:
            missing = set(blocks) - set(self._free)
            if missing:
                raise ValueError(
                    f"{rid}: blocks {sorted(missing)} already owned — the "
                    f"snapshot tables overlap")
        self.share(rid, blocks)

    def restore_index(self, entries: Sequence) -> None:
        """Re-register committed ``(block, parent, tokens)`` entries for
        LIVE blocks (refcount > 0) — the restore-time twin of
        :meth:`commit_block`, run after the snapshot's tables were
        re-adopted.  Entries whose block nobody re-adopted are skipped
        here; :meth:`admit_cached` is the path for ownerless warm
        blocks."""
        if not self.prefix_cache:
            return
        for block, parent, tokens in entries:
            if self._ref.get(int(block), 0) > 0:
                self._register(int(block), int(parent),
                               tuple(int(t) for t in tokens))

    def admit_cached(self, block: int, parent: int,
                     tokens: Sequence[int]) -> bool:
        """Restore-time cache admission: move a FREE block into the
        warm-KV cache tier under (parent, tokens) — the
        ``BlockManager.adopt`` counterpart for blocks nobody owns but
        whose pool pages still hold committed prefix KV (snapshots carry
        the warm cache across restarts).  Returns False (no-op) when the
        block is not free or the key is already indexed."""
        if block not in self._free or not self.prefix_cache:
            return False
        key = tuple(int(t) for t in tokens)
        if not self._register(block, int(parent), key):
            return False
        self._free.remove(block)
        self._cached[block] = None
        return True

    def free(self, rid: str) -> None:
        """Drop ``rid``'s claim on its blocks.  A block whose refcount
        reaches 0 returns to the free list — unless its contents are
        committed in the prefix index, in which case it enters the LRU
        cache tier instead (still counted by ``num_free``; reclaimed
        under allocation pressure)."""
        for b in reversed(self._tables.pop(rid)):
            self._ref[b] -= 1
            if self._ref[b] > 0:
                continue
            del self._ref[b]
            if b in self._meta:
                self._cached[b] = None   # warm-KV tier, LRU order
            else:
                self._orphan_children(b)
                self._free.append(b)

    # -- tables -----------------------------------------------------------

    def table(self, rid: str) -> list[int]:
        return list(self._tables[rid])

    def padded_table(self, rid: str, width: int) -> list[int]:
        """The request's block table padded to ``width`` logical pages
        with the null block (the engine's fixed-width device row)."""
        t = self._tables[rid]
        if len(t) > width:
            raise ValueError(
                f"{rid}: {len(t)} blocks exceed table width {width}")
        return t + [self.null_block] * (width - len(t))

    def capacity_tokens(self, rid: str) -> int:
        """Cache rows the request's current allocation can hold."""
        return len(self._tables[rid]) * self.page_size
