"""Crash-resilient serving: engine snapshot/restore + the token journal.

PR 3 contained faults *within* a live engine process; this module makes
the process itself expendable.  A TPU preemption, an OOM-kill, or a host
crash used to lose every in-flight request and every block of paged KV —
here the full serving state becomes durable and a fresh process resumes
every stream **bit-identically** to the uninterrupted run (the MegaScale
/ Llumnix primitive: snapshot + exactly-once replay).

Two cooperating artifacts live under one snapshot directory:

``journal.jsonl``
    An append-only token journal.  ``submit`` records (prompt, sampling
    params — including the PRNG seed whose per-token ``fold_in`` stream
    makes sampled recompute deterministic), one ``tok`` record per
    committed token (appended the moment the engine commits, BEFORE the
    ``on_token`` callback fires), and a ``fin`` record per retirement.
    The journal is flushed per record, so it is never behind the tokens
    the engine has emitted by more than the record being written.

``kv/<step>/``
    Orbax KV snapshots via :class:`runtime.checkpoint.CheckpointManager`
    (tmp-dir + rename: a kill mid-snapshot leaves the previous snapshot
    intact).  Each step dir holds the paged K/V pools plus a
    ``meta.json`` manifest written into the SAME rename barrier: engine
    geometry, block tables + free-list implied state, and per-request
    device state (kv_lens, pending token, slot, deadline-relevant
    timestamps).  The manifest also embeds each request's prompt,
    params, and emitted tokens, so a snapshot is self-contained even
    without the journal.

**The exactly-once argument.**  The journal is the source of truth for
*emission*; the KV snapshot is only an accelerator.  A token is emitted
iff it is journaled; generation is deterministic given (prompt, params,
emission index) — greedy by argmax, sampled via the per-request
``fold_in(key(seed), index)`` stream — so on restore:

- tokens **in** the journal are restored into ``generated`` and never
  re-derived → never double-emitted, even when the crash landed between
  the device KV commit and the journal append (the device-side token
  simply recomputes to the identical value);
- tokens the device committed but the journal never saw are re-derived
  bit-identically through the exact-recompute preemption path
  (``work_prompt = prompt + generated``) → never dropped.

When the KV snapshot lags the journal (incremental mode:
``snapshot_every=N`` steps while the journal appends per commit), the
journal-ahead suffix replays through that same recompute path; a request
whose journal count matches the snapshot resumes *in place* — pools,
block table, pending token — with zero recompute.  Restore onto a
DIFFERENT engine geometry degrades the same way: requests whose blocks
no longer fit re-queue through admission and recompute, and streams stay
bit-exact because the per-request token function never depended on the
geometry.  Quarantined (ERROR), shed, and expired requests restore as
*finished* — a poisoned request is never resurrected.

Callback delivery across the crash is at-most-once for the single
in-flight token (journaled, then the process died before its
``on_token`` ran); ``restore(..., replay_tokens=True)`` flips that to
at-least-once by re-firing callbacks for every journaled token.  The
emitted *stream* is exactly-once either way.

See docs/serving.md "Crash recovery"; chaos coverage lives in
tests/test_serve_recovery.py (kill/restart at every crash window).
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Union

import jax
import numpy as np

from triton_dist_tpu.runtime import checkpoint as ck
from triton_dist_tpu.runtime.faults import CORRUPT_ACTIONS, corrupt_bytes
from triton_dist_tpu.serve.integrity import (
    DOC_CRC,
    atomic_write_json,
    canonical_crc,
    crc32_bytes,
    rec_crc_ok,
    stamp_crc,
    verify_json_doc,
)
from triton_dist_tpu.serve.metrics import RequestMetrics
from triton_dist_tpu.serve.request import (
    FinishReason,
    Request,
    RequestOutput,
    SamplingParams,
)
from triton_dist_tpu.serve.scheduler import ReqState, Status

SNAPSHOT_FORMAT = 1
JOURNAL_NAME = "journal.jsonl"
KV_SUBDIR = "kv"
META_NAME = "meta.json"
#: meta.json's self-digest field (over the manifest minus this key)
META_CRC = "meta_crc"


class JournalCorrupt(RuntimeError):
    """A journal with INTERIOR damage (an undecodable or CRC-mismatched
    non-final line, or a token-index gap) — distinct from the tolerated
    torn FINAL line a crash mid-append leaves.  Carries the salvaged
    state (every record that still authenticates, ``state``) and the structured
    :class:`JournalDamage` report (``damage``): a caller that can
    salvage goes through :func:`salvage_journal`; one that cannot must
    fail loudly rather than silently absorb token loss."""

    def __init__(self, damage: "JournalDamage",
                 state: dict[str, "JournalRequest"]):
        super().__init__(str(damage))
        self.damage = damage
        self.state = state


class SnapshotCorrupt(RuntimeError):
    """A PUBLISHED snapshot failed digest verification (a pool leaf or
    the meta.json manifest) — bit rot, not a torn write (torn writes
    never survive the tmp-dir + rename publish and fall back to the
    previous step).  Never caught by the restore fallback walk: a
    corrupt snapshot must fail loudly naming the bad leaf, and the
    operator (or ``scripts/serve_fsck.py --salvage``) quarantines the
    step so restore can use an older snapshot + the journal."""


# ---------------------------------------------------------------------------
# The token journal
# ---------------------------------------------------------------------------


class TokenJournal:
    """Append-only JSONL journal of submissions, token commits, and
    retirements.  Flushed per record (optionally fsynced with
    ``fsync=True`` — the engine's ``journal_fsync``); :meth:`sync`
    forces durability at snapshot barriers regardless.

    **Group commit** (``fsync_interval_s=``, ROADMAP #5a): a per-record
    ``fsync`` costs a disk round trip per token — batching it to at most
    one fsync per interval keeps the power-loss window bounded by the
    interval instead of unbounded (flush-only) without paying the
    per-token sync.  ``sync()`` (the snapshot barrier) always fsyncs,
    so the KV snapshot can never publish ahead of the journal.

    **Compaction** (:meth:`rewrite`): the engine rewrites the journal at
    snapshot barriers — finished requests collapse into single ``done``
    records — through an atomic tmp + rename, so the file stops growing
    with every token ever served; a crash anywhere during the rewrite
    leaves either the old or the new journal whole.

    **Integrity framing** (docs/serving.md "Durability & integrity"):
    every appended/rewritten record carries a CRC32 of its canonical
    JSON under ``"c"`` — :func:`replay_journal` verifies per line and
    distinguishes a torn final line (tolerated, as ever) from interior
    corruption (loud salvage).  ``faults=`` threads the engine's
    injector so the ``integrity`` point can damage a line's bytes
    BEFORE they hit disk (the chaos seam the verifiers are proved
    against)."""

    def __init__(self, path: str | os.PathLike, *, fsync: bool = False,
                 fsync_interval_s: Optional[float] = None, faults=None):
        self.faults = faults
        self.path = os.path.abspath(os.fspath(path))
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # A tmp file is an aborted rewrite (the process died between
        # writing it and the rename): the original journal is whole, the
        # orphan is garbage.
        try:
            os.unlink(self.path + ".tmp")
        except OSError:
            pass
        self._heal_torn_tail()
        self._f = open(self.path, "a", encoding="utf-8")
        self.fsync = bool(fsync)
        self.fsync_interval_s = fsync_interval_s
        self._last_fsync = time.monotonic()
        self._dirty = False  # flushed-but-not-fsynced tail
        self.records = 0   # appended by THIS process (not the file total)
        self.bytes = 0
        self.file_bytes = os.path.getsize(self.path)

    def _heal_torn_tail(self) -> None:
        """Truncate a partial final line before appending: a crash
        mid-append leaves a torn record, and appending to it would glue
        the NEXT record onto the garbage — corrupting a healthy commit,
        not just the already-lost one.  Scans backward in windows, so a
        torn record of ANY size (a submit with a very long prompt can
        exceed one window) truncates to the last complete line rather
        than taking healthy earlier records with it."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if not size:
                return
            pos = size
            while pos > 0:
                back = min(pos, 1 << 16)
                f.seek(pos - back)
                chunk = f.read(back)
                if pos == size and chunk.endswith(b"\n"):
                    return            # tail is whole
                cut = chunk.rfind(b"\n")
                if cut >= 0:
                    f.truncate(pos - back + cut + 1)
                    return
                pos -= back
            f.truncate(0)             # a single torn line was the file

    def append(self, rec: dict) -> None:
        rec = stamp_crc(rec)
        body = json.dumps(rec, separators=(",", ":"))
        if self.faults is not None:
            act = self.faults.fire("integrity", op="journal",
                                   rid=rec.get("rid"))
            if act in CORRUPT_ACTIONS:
                # damage the LINE bytes, keep the line framing: the
                # corruption lands inside one record, which is exactly
                # the interior-damage class replay must catch loudly
                raw = corrupt_bytes(body.encode("utf-8"), act)
                body = raw.decode("utf-8", errors="replace")
        line = body + "\n"
        self._f.write(line)
        self._f.flush()
        self._dirty = True
        if self.fsync:
            os.fsync(self._f.fileno())
            self._last_fsync = time.monotonic()
            self._dirty = False
        elif self.fsync_interval_s is not None:
            now = time.monotonic()
            if now - self._last_fsync >= self.fsync_interval_s:
                os.fsync(self._f.fileno())
                self._last_fsync = now
                self._dirty = False
        self.records += 1
        self.bytes += len(line)
        self.file_bytes += len(line)

    def submit(self, req: Request) -> None:
        rec = {"t": "submit", "rid": req.request_id,
               "prompt": [int(x) for x in req.prompt],
               "params": req.params.to_dict(),
               "slo": req.slo_class,
               "ts": req.arrival_time}
        if getattr(req, "trace", None):
            # the distributed-tracing context rides the journal so a
            # crash-path manifest (manifest_from_journal) hands the
            # journey — trace id + hop — to the adopting replica
            rec["trace"] = req.trace
        self.append(rec)

    def token(self, rid: str, index: int, tok: int, ts: float) -> None:
        self.append({"t": "tok", "rid": rid, "i": int(index),
                     "tok": int(tok), "ts": ts})

    def finish(self, rid: str, reason: str, error: Optional[str],
               n_tokens: int, ts: float) -> None:
        self.append({"t": "fin", "rid": rid, "reason": reason,
                     "err": error, "n": int(n_tokens), "ts": ts})

    def migrate(self, rid: str, n_tokens: int, ts: float) -> None:
        """Record a live-migration hand-off: ``rid`` left this engine
        for another replica (docs/serving.md "Fleet serving").  The
        record is the ownership transfer — a restore of THIS journal
        must never resurrect the request (the target replica's journal
        now owns its remaining stream), which is exactly what makes the
        cross-replica token union exactly-once."""
        self.append({"t": "mig", "rid": rid, "n": int(n_tokens),
                     "ts": ts})

    def sync(self) -> None:
        """Force everything appended so far to disk (snapshot barrier)."""
        self._f.flush()
        os.fsync(self._f.fileno())
        self._last_fsync = time.monotonic()
        self._dirty = False

    def maybe_sync(self) -> None:
        """Group-commit deadline sweep — the engine calls this every
        step.  ``append`` only checks the fsync interval when the NEXT
        record arrives, so without a sweep the last record of a burst
        would sit in the OS page cache for as long as traffic pauses —
        exactly the unbounded power-loss window ``fsync_interval_s``
        exists to bound."""
        if (self._dirty and self.fsync_interval_s is not None
                and time.monotonic() - self._last_fsync
                >= self.fsync_interval_s):
            self.sync()

    def rewrite(self, records: list[dict]) -> None:
        """Atomically replace the journal's contents with ``records``
        (the engine's snapshot-barrier compaction).  tmp + fsync +
        rename: readers and a crash at any instant see either the old
        journal or the complete new one, never a torn mix.  Every
        record is (re-)stamped with its CRC framing — compaction
        produces fresh record shapes, so digests must be recomputed."""
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in records:
                f.write(json.dumps(stamp_crc(rec),
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")
        self._last_fsync = time.monotonic()
        self._dirty = False
        self.file_bytes = os.path.getsize(self.path)

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # noqa: BLE001 — crash-path best effort
            pass


@dataclass
class JournalRequest:
    """One request's journal view after :func:`replay_journal`."""

    rid: str
    prompt: Optional[np.ndarray] = None
    params: Optional[SamplingParams] = None
    arrival: Optional[float] = None
    tokens: dict = field(default_factory=dict)   # index -> (tok, ts)
    finish: Optional[dict] = None                # {"reason","err","n","ts"}
    # ownership left this journal via a live-migration hand-off ("mig"
    # record): restore must not resurrect the request, and the request
    # is not part of this engine's finish accounting either
    migrated: bool = False
    # first-token timestamp carried by rotation records ("ftt"): the
    # compacted tts/ts lists None-pad their head past the bounded
    # token-time window, so the restored TTFT needs this explicitly
    first_tok: Optional[float] = None
    # distributed-tracing context from the submit record ({"trace_id",
    # "hop"}) — crash-path manifests carry it so the journey survives
    # the replica (docs/observability.md "Fleet observability")
    trace: Optional[dict] = None
    # SLO class from the submit record — a restored/migrated request
    # keeps its service tier ("interactive" covers pre-slo journals)
    slo: str = "interactive"

    def token_list(self) -> list[int]:
        """Emitted tokens in order (the contiguous prefix from 0).  A
        gap is journal corruption — :func:`scan_journal` reports it as
        damage (never silently absorbed; the pre-integrity silent
        truncation was the ISSUE-20 bug) and the salvage keeps exactly
        this contiguous prefix."""
        out = []
        i = 0
        while i in self.tokens:
            out.append(self.tokens[i][0])
            i += 1
        return out

    def token_times(self) -> list[float]:
        out = []
        i = 0
        while i in self.tokens:
            out.append(self.tokens[i][1])
            i += 1
        return out


def _apply_record(out: dict[str, JournalRequest], rec: dict) -> None:
    """Fold one decoded journal record into the replay state (shared by
    the salvage scan and any future incremental reader)."""
    rid = rec.get("rid")
    if rid is None:
        return
    jr = out.setdefault(rid, JournalRequest(rid=rid))
    t = rec.get("t")
    if t == "submit":
        if jr.prompt is None:
            jr.prompt = np.asarray(rec["prompt"], np.int32)
            jr.params = SamplingParams.from_dict(rec["params"])
            jr.arrival = rec.get("ts")
            jr.slo = rec.get("slo", "interactive")
            if jr.first_tok is None:
                jr.first_tok = rec.get("ftt")
            if jr.trace is None:
                jr.trace = rec.get("trace")
        # a submit AFTER a mig receipt re-opens ownership: the
        # request was handed off (push/drain) and later
        # re-admitted HERE (the disagg push fallback path) —
        # this journal owns its stream again, and a crash must
        # recover it rather than skip it as migrated
        jr.migrated = False
    elif t == "tok":
        jr.tokens.setdefault(int(rec["i"]),
                             (int(rec["tok"]), rec.get("ts")))
    elif t == "fin" and jr.finish is None:
        jr.finish = {"reason": rec["reason"],
                     "err": rec.get("err"),
                     "n": rec.get("n"), "ts": rec.get("ts")}
    elif t == "mig":
        jr.migrated = True
    elif t == "done":
        # One-line compacted request (a snapshot-barrier journal
        # rotation): submit + every tok + fin folded together.
        if jr.prompt is None:
            jr.prompt = np.asarray(rec["prompt"], np.int32)
            jr.params = SamplingParams.from_dict(rec["params"])
            jr.arrival = rec.get("arrival")
            jr.slo = rec.get("slo", "interactive")
        if jr.first_tok is None:
            jr.first_tok = rec.get("ftt")
        tts = rec.get("tts") or []
        for i, tok in enumerate(rec.get("toks", [])):
            jr.tokens.setdefault(
                i, (int(tok), tts[i] if i < len(tts) else None))
        if jr.finish is None:
            jr.finish = {"reason": rec["reason"],
                         "err": rec.get("err"),
                         "n": len(rec.get("toks", [])),
                         "ts": rec.get("fts")}


@dataclass
class JournalDamage:
    """Structured damage report for a corrupt journal (what the salvage
    kept and what it lost) — the payload of :class:`JournalCorrupt`,
    the ``corrupt`` trace event, and the crash-path manifest's
    ``damage`` field."""

    path: str
    #: (1-based line number, reason) per damaged line — every line the
    #: salvage skipped (the records around them still apply: each line
    #: authenticates independently)
    bad_lines: list = field(default_factory=list)
    #: (rid, first missing token index) per token-index gap — damage
    #: even in a pre-integrity journal (an interior tok line vanished)
    gaps: list = field(default_factory=list)
    #: rids that lost records (bad-line owners where readable, gap
    #: owners, rids dropped for a rotted submit)
    affected_rids: list = field(default_factory=list)
    #: last contiguous token index the salvage kept, per affected rid
    #: (-1 when nothing of the stream survived)
    last_good_tok: dict = field(default_factory=dict)
    total_lines: int = 0
    salvaged_lines: int = 0
    #: where the damaged original went (``journal.jsonl.corrupt-<ts>``),
    #: once :func:`salvage_journal` quarantined it
    quarantine: Optional[str] = None

    def summary(self) -> dict:
        """JSON-able form (wire manifests, trace events)."""
        return {
            "path": self.path,
            "bad_lines": [[int(n), why] for n, why in self.bad_lines],
            "gaps": [[rid, int(i)] for rid, i in self.gaps],
            "affected_rids": list(self.affected_rids),
            "last_good_tok": {r: int(i)
                              for r, i in self.last_good_tok.items()},
            "total_lines": self.total_lines,
            "salvaged_lines": self.salvaged_lines,
            "quarantine": self.quarantine,
        }

    def __str__(self) -> str:
        first = self.bad_lines[0] if self.bad_lines else None
        what = (f"line {first[0]} ({first[1]})" if first
                else f"token gap {self.gaps[0]}" if self.gaps
                else "damage")
        return (f"journal {self.path} corrupt at {what}: salvaged "
                f"{self.salvaged_lines}/{self.total_lines} lines, "
                f"{len(self.affected_rids)} request(s) affected "
                f"({', '.join(self.affected_rids[:4])}"
                f"{'...' if len(self.affected_rids) > 4 else ''})")


def scan_journal(path: str | os.PathLike) \
        -> tuple[dict[str, JournalRequest], Optional[JournalDamage]]:
    """Parse a journal into per-request state (submit order) plus a
    damage report when the file holds more than crash-shaped damage.

    The tolerance contract (pinned by tests): a torn FINAL line — the
    one shape a crash mid-append leaves — is healed silently, exactly
    as before.  Everything else is damage, and the salvage keeps every
    record that still AUTHENTICATES: records are independently
    CRC-framed and self-describing (explicit token indices,
    first-submit-wins, idempotent fin/mig receipts), so a rotted line
    costs exactly the records on that line, not the suffix behind it —
    at fleet scale the suffix holds migrated-in submits whose prompts
    exist nowhere else.  A skipped tok line surfaces as a token-index
    gap (also a pre-integrity journal's only corruption signature) that
    truncates that rid to its contiguous prefix; a rid whose submit
    line rotted is dropped from state entirely (its prompt is
    unrecoverable here).  Both are REPORTED, never silently absorbed.
    Pre-integrity records (no ``"c"`` field) are accepted unverified —
    back-compat.  Returns ``({}, None)`` when no journal exists."""
    out: dict[str, JournalRequest] = {}
    if not os.path.exists(path):
        return out, None
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    n_content = len(lines)
    while n_content and not lines[n_content - 1].strip():
        n_content -= 1  # trailing blank lines are not records
    bad: list = []
    affected: list[str] = []
    salvaged = 0
    for idx in range(n_content):
        line = lines[idx].strip()
        if not line:
            salvaged += 1
            continue
        why = None
        rec = None
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            why = "undecodable"
        if rec is not None and rec_crc_ok(rec) is False:
            why = "crc mismatch"
        if (why == "undecodable" and idx == n_content - 1
                and not lines[idx].endswith("\n")):
            # the torn final line a crash mid-append leaves (buffered
            # writes land prefixes, so a torn record never has its
            # newline): healed, not damage.  A newline-TERMINATED
            # garbage final line — or a CRC mismatch on a parseable
            # one — is real corruption: a torn write cannot re-close
            # the framing.
            break
        if why is not None:
            bad.append((idx + 1, why))
            # best-effort owner classification (report only — a record
            # that failed its CRC is never applied to state)
            if rec is not None:
                rid = rec.get("rid")
                if rid is not None and rid not in affected:
                    affected.append(rid)
            continue
        _apply_record(out, rec)
        salvaged += 1
    damage: Optional[JournalDamage] = None
    # a rid whose submit line ROTTED leaves orphan tok/fin records with
    # no prompt to recompute from: drop it from state (a half request
    # must not reach placement) and report it lost.  Only when damage
    # was seen — an undamaged journal that opens mid-stream (tok lines
    # with no submit) is the long-tolerated partial-state shape
    if bad:
        for rid in [r for r, jr in out.items()
                    if jr.prompt is None and not jr.migrated]:
            del out[rid]
            if rid not in affected:
                affected.append(rid)
    # token-index gaps inside the trusted records: the pre-integrity
    # corruption signature (a deleted/garbled interior tok line whose
    # loss JSON alone cannot see) — report it and truncate the stream
    # to its contiguous prefix instead of silently absorbing it
    gaps: list = []
    for rid, jr in out.items():
        if not jr.tokens:
            continue
        contiguous = len(jr.token_list())
        if max(jr.tokens) + 1 > contiguous:
            gaps.append((rid, contiguous))
            jr.tokens = {i: jr.tokens[i] for i in range(contiguous)}
            if rid not in affected:
                affected.append(rid)
    if bad or gaps:
        damage = JournalDamage(
            path=os.path.abspath(os.fspath(path)), bad_lines=bad,
            gaps=gaps, affected_rids=affected,
            last_good_tok={rid: len(out[rid].token_list()) - 1
                           if rid in out else -1 for rid in affected},
            total_lines=n_content, salvaged_lines=salvaged)
    return out, damage


def replay_journal(path: str | os.PathLike) -> dict[str, JournalRequest]:
    """Parse a journal into per-request state, in submit order.

    Tolerant of exactly the damage a crash can cause: a torn final line
    (the process died mid-append) is healed, and a duplicate record
    keeps its first occurrence.  Returns ``{}`` when no journal exists.
    ANY other damage — an interior undecodable line, a CRC mismatch, a
    token-index gap — raises :class:`JournalCorrupt` (carrying the
    salvaged state + damage report): silent absorption of committed
    tokens was the bug this layer exists to kill.  Callers that own the
    directory and can quarantine go through :func:`salvage_journal`."""
    state, damage = scan_journal(path)
    if damage is not None:
        raise JournalCorrupt(damage, state)
    return state


def _serialize_state(state: dict[str, JournalRequest]) -> list[dict]:
    """Re-serialize replayed state as plain journal records (the
    salvage writer): submit + contiguous toks + fin/mig per request, in
    submit order.  Equivalent-for-replay to the damaged journal's
    surviving records."""
    recs: list[dict] = []
    for rid, jr in state.items():
        if jr.prompt is not None:
            rec = {"t": "submit", "rid": rid,
                   "prompt": [int(x) for x in jr.prompt],
                   "params": jr.params.to_dict(),
                   "slo": jr.slo, "ts": jr.arrival}
            if jr.first_tok is not None:
                rec["ftt"] = jr.first_tok
            if jr.trace is not None:
                rec["trace"] = jr.trace
            recs.append(rec)
        for i, tok in enumerate(jr.token_list()):
            recs.append({"t": "tok", "rid": rid, "i": i,
                         "tok": int(tok), "ts": jr.tokens[i][1]})
        if jr.finish is not None:
            recs.append({"t": "fin", "rid": rid,
                         "reason": jr.finish["reason"],
                         "err": jr.finish.get("err"),
                         "n": jr.finish.get("n"),
                         "ts": jr.finish.get("ts")})
        if jr.migrated:
            recs.append({"t": "mig", "rid": rid,
                         "n": len(jr.token_list()),
                         "ts": jr.arrival or 0.0})
    return recs


def quarantine_path(path: str) -> str:
    """The ``<journal>.corrupt-<ts>`` name a damaged original moves to
    (unique even for same-second salvages)."""
    base = f"{path}.corrupt-{int(time.time())}"
    cand, n = base, 0
    while os.path.exists(cand):
        n += 1
        cand = f"{base}.{n}"
    return cand


def salvage_journal(path: str | os.PathLike, *, quarantine: bool = True) \
        -> tuple[dict[str, JournalRequest], Optional[JournalDamage]]:
    """Replay ``path`` with salvage semantics: an undamaged (or merely
    torn-tail) journal returns ``(state, None)`` untouched; a corrupt
    one QUARANTINES the damaged original (``journal.jsonl.corrupt-<ts>``
    — evidence survives for the postmortem, and no later writer appends
    onto rot) and atomically rewrites ``path`` with every record that
    still authenticates, CRC-framed, before anything else touches it.
    Returns the salvaged state + the damage report; the caller owns the
    LOUD part (counter, ``corrupt`` trace event, re-queue escalation)."""
    state, damage = scan_journal(path)
    if damage is None:
        return state, None
    path = os.path.abspath(os.fspath(path))
    if quarantine:
        qp = quarantine_path(path)
        os.replace(path, qp)
        damage.quarantine = qp
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in _serialize_state(state):
                f.write(json.dumps(stamp_crc(rec),
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    print(f"[recovery] {damage}"
          + (f"; original quarantined at {damage.quarantine}"
             if damage.quarantine else ""), file=sys.stderr)
    return state, damage


# ---------------------------------------------------------------------------
# Snapshot
# ---------------------------------------------------------------------------


def _pool_tree(engine) -> dict:
    """The paged pools as a flat dict orbax round-trips losslessly.

    Spec engines (PR 7) also carry the DRAFT's device state: the
    slot-indexed batch caches, its lengths/logits, and the target's
    round-opening logits — everything a restored spec engine needs to
    resume rounds IN PLACE instead of re-prefilling every draft row
    through the preemption path (the recorded PR 5 follow-up).  A
    BAILED-OUT engine (``_spec_off``) snapshots pools-only: its draft
    state is untrusted by definition — and may reference buffers a
    failed chain's donation consumed, which orbax could not serialize
    anyway (the manifest omits ``draft`` in lockstep, so the reader
    never expects the keys)."""
    tree = {}
    for i, (k, v) in enumerate(engine._pools):
        if isinstance(k, dict):
            # int8 pools: quant + scale planes snapshot AS THEY ARE —
            # restore adopts the bytes verbatim (a dequant/requant round
            # trip would break bit-exactness; quantization isn't
            # idempotent)
            tree[f"l{i}_k_q"] = k["q"]
            tree[f"l{i}_k_s"] = k["s"]
            tree[f"l{i}_v_q"] = v["q"]
            tree[f"l{i}_v_s"] = v["s"]
        else:
            tree[f"l{i}_k"] = k
            tree[f"l{i}_v"] = v
    if engine.spec_k and not engine._spec_off:
        sd = engine._draft_state
        for i, (k, v) in enumerate(sd.caches):
            tree[f"d{i}_k"] = k
            tree[f"d{i}_v"] = v
        tree["draft_kv_lens"] = sd.kv_lens
        tree["draft_last_logits"] = sd.last_logits
        tree["spec_last_logits"] = engine._last_logits
    return tree


def _capture_meta(engine, now: float, *, journal_here: bool) -> dict:
    reqs = {}
    for rid, rs in engine._states.items():
        if rid.startswith("__warmup_") or rs.status is Status.FINISHED:
            continue
        reqs[rid] = {
            "status": rs.status.value,
            "slot": rs.slot,
            "kv_len": rs.kv_len,
            "gen": [int(t) for t in rs.generated],
            "pending": (int(rs.pending_token)
                        if rs.pending_token is not None else None),
            "seq": rs.seq,
            "cb_off": rs.callback_disabled,
            "arrival": rs.req.arrival_time,
            "prompt": [int(x) for x in np.asarray(rs.req.prompt)],
            "params": rs.req.params.to_dict(),
            "slo": rs.req.slo_class,
            "first_sched": rs.metrics.first_scheduled_time,
            "first_tok": rs.metrics.first_token_time,
            "token_times": list(rs.metrics.token_times),
            "n_preempt": rs.metrics.n_preemptions,
            "cached_prefix": rs.cached_prefix,
            "committed_pages": rs.committed_pages,
        }
    # Finished requests ride the manifest only when this directory has
    # no co-located journal to carry them (a one-shot snapshot to a
    # foreign dir): with the journal here, every retirement already has
    # its submit/tok/fin records (restore backfills prior lives), and
    # re-serializing the full served history into every capture would
    # make the snapshot hot-path cost grow with total requests served.
    outs = {}
    if not journal_here:
        for rid, out in engine._outputs.items():
            if rid.startswith("__warmup_"):
                continue
            outs[rid] = {
                "prompt": [int(x) for x in np.asarray(out.prompt)],
                "tokens": [int(t) for t in out.token_ids],
                "reason": out.finish_reason.value,
                "error": out.error,
                "arrival": out.metrics.arrival_time,
            }
    cfg = engine.cfg
    eng_meta = {
        "num_blocks": engine.bm.num_blocks,
        "page_size": engine.page,
        "max_batch": engine.max_batch,
        "max_seq": engine.gen.max_seq,
        "prefill_chunk": engine.scheduler.prefill_chunk,
        "prefill_budget": engine.scheduler.prefill_budget,
        "horizon": engine.horizon,
        "pipeline": engine.pipeline,
        "spec_k": engine.spec_k,
        "spec_fused": engine.spec_fused,
        "prefix_cache": engine.prefix_cache,
        "snapshot_every": engine.snapshot_every,
        "n_layers": cfg.n_layers,
        "n_kv_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "vocab": cfg.vocab,
        "kv_dtype": str(np.dtype(cfg.dtype)),
        # int8 pools change the tree layout (l{i}_k_q/_s planes) AND the
        # restore contract: quantized restores only into quantized
        # (tolerated absent by the reader — pre-quant snapshots are fp).
        "kv_quant": engine.kv_quant,
    }
    if engine.mesh is not None:
        # Mesh/sharding spec (docs/serving.md "Sharded serving"):
        # recorded so operators (and the fleet controller) can see what
        # layout produced a snapshot — restore does NOT require it.
        # Pools are saved as GLOBAL arrays (orbax assembles shards), so
        # a snapshot restores onto ANY mesh shape: the restoring
        # engine's own mesh= override decides the new layout, pools are
        # re-laid-out by one device_put, and block tables that violate
        # the new partition placement (seq layouts of a different
        # world) re-queue through exact recompute.  Tolerated absent by
        # every reader (pre-mesh snapshots restore fine).
        eng_meta["mesh"] = {
            "world": engine.mesh_world,
            "axis": engine.tp_axis,
            "kv_shard": engine.kv_shard,
            # 2D layouts record both axes (tolerated absent by every
            # reader — 1D and pre-mesh snapshots omit them)
            "sp_axis": engine.sp_axis,
            "sp_world": engine.sp_world,
        }
    if engine.spec_k and not engine._spec_off:
        # Draft-state geometry: the snapshot reader needs it to build
        # abstract targets for the draft arrays in the pool tree, and
        # restore checks it against the caller's draft before resuming
        # spec rows in place (mismatch -> exact-recompute requeue).
        # Omitted in lockstep with _pool_tree's draft subtree (a
        # spec_off snapshot is pools-only).
        dcfg = engine.draft.cfg
        eng_meta["draft"] = {
            "n_layers": dcfg.n_layers,
            "n_kv_heads": dcfg.n_kv_heads,
            "head_dim": dcfg.head_dim,
            "max_seq": engine.draft.max_seq,
            "vocab": dcfg.vocab,
            "dtype": str(np.dtype(dcfg.dtype)),
        }
    return {
        "format": SNAPSHOT_FORMAT,
        "clock": now,
        "engine": eng_meta,
        "spec_off": engine._spec_off,
        "seq_counter": engine.scheduler._seq,
        "waiting": [rs.req.request_id for rs in engine.scheduler.waiting
                    if not rs.req.request_id.startswith("__warmup_")],
        "tables": {rid: list(t) for rid, t in engine.bm._tables.items()
                   if not rid.startswith("__warmup_")},
        # Prefix cache (docs/serving.md "Prefix caching"): the content
        # index [block, parent, tokens-in-block] plus the LRU order of
        # the warm cache tier — restore re-registers live shared blocks
        # and re-admits the tier, so the warm cache survives a restart
        # (admit_cached as cache admission, the ROADMAP #3 design).
        "prefix": {
            "index": [[b, p, list(t)] for b, (p, t)
                      in engine.bm._meta.items()],
            "cached": [int(b) for b in engine.bm._cached],
        },
        "requests": reqs,
        "outputs": outs,
        # flight-recorder tail (serve/trace.py): the newest engine
        # events ride every snapshot, so a restored engine's ring opens
        # with its previous life's trail — postmortems after a restart
        # still see what led up to the crash (tolerated absent by the
        # reader: pre-PR-8 snapshots restore fine).
        "flight": (engine.trace.tail(256)
                   if getattr(engine, "trace", None) is not None
                   else []),
    }


def snapshot_engine(engine, directory: str | os.PathLike) -> dict:
    """Durably capture ``engine``'s full serving state under
    ``directory`` (called between steps — no dispatch may be in
    flight).  Returns ``{"step", "ms"}``; latency and counts land in
    ``engine.metrics`` (``summary()["recovery"]``).

    Ordering is the correctness contract: the journal syncs FIRST (the
    KV snapshot may lag the journal, never the reverse), then pools +
    manifest publish atomically through the checkpoint manager's
    tmp-dir + rename barrier.  The ``snapshot`` fault point fires twice
    per capture — before the KV write (call 2k+1) and inside the
    tmp-written-but-unrenamed window (call 2k+2) — so the chaos tests
    can land a kill in either crash window.
    """
    t0 = time.perf_counter()
    directory = os.path.abspath(os.fspath(directory))
    os.makedirs(directory, exist_ok=True)
    now = engine._clock()
    journal_here = (engine._journal is not None
                    and os.path.dirname(engine._journal.path) == directory)
    if engine._journal is not None:
        engine._journal.sync()
    meta = _capture_meta(engine, now, journal_here=journal_here)
    if engine.faults is not None:
        engine.faults.fire("snapshot")
    tree = _pool_tree(engine)
    # Leaf digests + manifest self-digest (docs/serving.md "Durability
    # & integrity"): meta.json records a CRC32 per pool leaf and one
    # over itself, computed from the in-memory arrays BEFORE the bytes
    # hit disk — restore verifies against exactly what the engine
    # meant to persist, so stored-byte rot can never restore as
    # subtly-wrong KV.
    meta["digests"] = {
        name: crc32_bytes(np.ascontiguousarray(
            np.asarray(arr)).tobytes())
        for name, arr in tree.items()}
    meta[META_CRC] = canonical_crc(meta, exclude=(META_CRC,))
    if engine.faults is not None:
        # integrity chaos, the SILENT-rot class: damage one leaf after
        # its digest was recorded and before the bytes hit disk.  The
        # published checkpoint is internally valid (tensorstore's own
        # framing CRC passes, orbax restores it without complaint) —
        # only the meta.json leaf digests can refuse it at restore.
        act = engine.faults.fire("integrity", op="snapshot")
        if act in CORRUPT_ACTIONS:
            _corrupt_pool_leaf(tree, act)
    # The home-directory manager is cached on the engine: its init
    # scans the directory (stale-.tmp GC + cross-host sync) — once is
    # enough on the periodic capture path that snapshot_ms meters.  A
    # one-shot snapshot to a FOREIGN directory must not disturb the
    # home state: it gets its own manager and step numbering, and the
    # engine's periodic cadence (_snap_seq, cached manager) is
    # untouched.
    kvdir = os.path.abspath(os.path.join(directory, KV_SUBDIR))
    home = (engine.snapshot_dir is not None
            and os.path.abspath(engine.snapshot_dir) == directory)
    mgr = engine._snap_mgr if home else None
    if mgr is None or mgr.directory != kvdir:
        mgr = ck.CheckpointManager(kvdir, max_to_keep=2)
        if home:
            engine._snap_mgr = mgr
    hook = None
    if engine.faults is not None:
        def hook(_tmp_path, _f=engine.faults):
            _f.fire("snapshot")
    if home:
        step = engine._snap_seq
    else:
        last = mgr.latest_step()
        step = 0 if last is None else last + 1
    mgr.save(step, tree,
             extras={META_NAME: json.dumps(meta)},
             on_before_finalize=hook)
    if home:
        engine._snap_seq = step + 1
    ms = (time.perf_counter() - t0) * 1e3
    m = engine.metrics
    m.snapshots += 1
    m.snapshot_ms_last = ms
    m.snapshot_ms_total += ms
    return {"step": step, "ms": ms}


def _corrupt_pool_leaf(tree: dict, action: str) -> Optional[str]:
    """Rot one pool leaf IN MEMORY (the ``op="snapshot"`` integrity
    seam): picks the largest leaf, corrupts its bytes, and rebuilds it
    at the original shape/dtype (truncation zero-fills the tail) so
    the checkpoint write itself succeeds.  Because the rot lands after
    the digest was recorded and before serialization, the stored step
    is internally valid — only the restore-time digest check can catch
    it.  Returns the rotted leaf name."""
    if not tree:
        return None
    name = max(sorted(tree),
               key=lambda n: np.asarray(tree[n]).nbytes)
    arr = np.ascontiguousarray(np.asarray(tree[name]))
    raw = arr.tobytes()
    rot = (corrupt_bytes(raw, action) + b"\x00" * len(raw))[:len(raw)]
    tree[name] = np.frombuffer(rot, dtype=arr.dtype).reshape(arr.shape)
    return name


def _corrupt_snapshot_leaf(step_dir: str, action: str) -> Optional[str]:
    """Damage the largest READ-PATH data file under a published
    ``step_dir`` (test/fsck utility for the on-disk rot class).  The
    per-process OCDBT staging copies (``ocdbt.process_*``) are skipped
    — restore never reads them, so damage there is invisible.  Note
    tensorstore frames its b-tree nodes with its own CRC-32C, so this
    class surfaces as a restore ERROR (torn-snapshot fallback), not as
    silently-wrong values — the in-memory seam above is what exercises
    the digest check.  Returns the damaged path."""
    best, size = None, -1
    for root, dirs, files in os.walk(step_dir):
        dirs[:] = [d for d in dirs if not d.startswith("ocdbt.process")]
        for name in files:
            if name.endswith(".json"):
                continue
            p = os.path.join(root, name)
            s = os.path.getsize(p)
            if s > size:
                best, size = p, s
    if best is None:
        return None
    with open(best, "rb") as f:
        data = f.read()
    with open(best, "wb") as f:
        f.write(corrupt_bytes(data, action))
    return best


def verify_snapshot_step(step_dir: str | os.PathLike) -> list[dict]:
    """Offline digest verification of one published snapshot step (the
    ``scripts/serve_fsck.py`` core): returns per-artifact findings
    ``{"artifact", "ok", "why"}`` — meta.json's self-digest first, then
    every pool leaf against its recorded digest.  A pre-integrity
    snapshot (no digests) reports a single unverified finding."""
    step_dir = os.path.abspath(os.fspath(step_dir))
    out: list[dict] = []
    meta_path = os.path.join(step_dir, META_NAME)
    try:
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
    except Exception as e:  # noqa: BLE001 — unreadable IS the finding
        return [{"artifact": meta_path, "ok": False,
                 "why": f"unreadable: {e}"}]
    mc = meta.get(META_CRC)
    if mc is None:
        return [{"artifact": meta_path, "ok": True,
                 "why": "pre-integrity snapshot (no digests): "
                        "unverified"}]
    if int(mc) != canonical_crc(meta, exclude=(META_CRC,)):
        return [{"artifact": meta_path, "ok": False,
                 "why": "meta.json self-digest mismatch"}]
    out.append({"artifact": meta_path, "ok": True, "why": "digest ok"})
    digs = meta.get("digests") or {}
    try:
        like = _abstract_pool_tree(meta)
        pools = ck.restore(step_dir, like)
    except Exception as e:  # noqa: BLE001 — unreadable IS the finding
        out.append({"artifact": step_dir, "ok": False,
                    "why": f"pool tree unreadable: {e}"})
        return out
    for name in sorted(like):
        want = digs.get(name)
        got = crc32_bytes(np.ascontiguousarray(
            np.asarray(pools[name])).tobytes())
        if want is None:
            out.append({"artifact": f"{step_dir}:{name}", "ok": False,
                        "why": "no recorded digest for leaf"})
        elif int(want) != got:
            out.append({"artifact": f"{step_dir}:{name}", "ok": False,
                        "why": f"leaf digest mismatch "
                               f"(recorded {want}, stored {got})"})
        else:
            out.append({"artifact": f"{step_dir}:{name}", "ok": True,
                        "why": "digest ok"})
    return out


def has_restorable_state(directory: str | os.PathLike) -> bool:
    """True when :func:`restore_engine` has anything to rebuild from: a
    non-empty journal or at least one PUBLISHED KV snapshot step.  A
    bare ``journal.jsonl`` the constructor touched before the process
    died carries no state — resuming from it would fail, and a fresh
    engine may safely reopen the directory."""
    d = os.fspath(directory)
    j = os.path.join(d, JOURNAL_NAME)
    if os.path.exists(j) and os.path.getsize(j) > 0:
        return True
    kvdir = os.path.join(d, KV_SUBDIR)
    if not os.path.isdir(kvdir):
        return False
    return any(name.isdigit() for name in os.listdir(kvdir))


def _abstract_pool_tree(meta: dict) -> dict:
    """ShapeDtypeStruct targets for a snapshot manifest's pool tree —
    the reader-side twin of :func:`_pool_tree` (shared by restore and
    the offline fsck verifier)."""
    e = meta["engine"]
    dtype = np.dtype(e["kv_dtype"])
    shape = (e["num_blocks"], e["n_kv_heads"], e["page_size"],
             e["head_dim"])
    like = {}
    if e.get("kv_quant"):
        s_shape = shape[:3]
        for i in range(e["n_layers"]):
            for kv in ("k", "v"):
                like[f"l{i}_{kv}_q"] = jax.ShapeDtypeStruct(
                    shape, np.int8)
                like[f"l{i}_{kv}_s"] = jax.ShapeDtypeStruct(
                    s_shape, np.float32)
    else:
        for i in range(e["n_layers"]):
            like[f"l{i}_k"] = jax.ShapeDtypeStruct(shape, dtype)
            like[f"l{i}_v"] = jax.ShapeDtypeStruct(shape, dtype)
    d = e.get("draft")
    if e.get("spec_k") and d and "vocab" in e:
        # Spec snapshots carry the draft's device state in the
        # same tree (see _pool_tree); the manifest's draft
        # geometry shapes the abstract targets.  Pre-PR-7
        # manifests lack "draft" and restore pools-only.
        ddt = np.dtype(d["dtype"])
        dshape = (e["max_batch"], d["n_kv_heads"], d["max_seq"],
                  d["head_dim"])
        for i in range(d["n_layers"]):
            like[f"d{i}_k"] = jax.ShapeDtypeStruct(dshape, ddt)
            like[f"d{i}_v"] = jax.ShapeDtypeStruct(dshape, ddt)
        like["draft_kv_lens"] = jax.ShapeDtypeStruct(
            (e["max_batch"],), np.int32)
        like["draft_last_logits"] = jax.ShapeDtypeStruct(
            (e["max_batch"], d["vocab"]), np.float32)
        like["spec_last_logits"] = jax.ShapeDtypeStruct(
            (e["max_batch"], e["vocab"]), np.float32)
    return like


def _load_latest_snapshot(directory: str) -> Optional[tuple]:
    """(step, meta, pools dict) for the newest READABLE snapshot, or
    None.  Walks newest → oldest like ``restore_latest`` — a snapshot
    torn by a concurrent kill falls back to the previous one.  Opens
    the manager read-only (``clean_tmp=False``): restore may run while
    another process is mid-snapshot (a standby peeking at a live
    engine's directory), and GC-ing ``.tmp`` here would tear that
    writer's save; orphans are reclaimed by the next WRITER instead
    (the restored engine's first snapshot).

    Digest verification (docs/serving.md "Durability & integrity"):
    a snapshot whose meta.json self-digest or pool-leaf digest
    mismatches raises :class:`SnapshotCorrupt` LOUDLY, naming the bad
    leaf — it never joins the torn-write fallback walk, because orbax
    restores a flipped bit without complaint and walking past would
    either adopt subtly-wrong KV or silently resume from stale state.
    Pre-integrity snapshots (no digests) restore with a one-line
    unverified warning."""
    kvdir = os.path.join(directory, KV_SUBDIR)
    if not os.path.isdir(kvdir):
        return None
    mgr = ck.CheckpointManager(kvdir, max_to_keep=2, clean_tmp=False)
    for step in reversed(mgr.all_steps()):
        step_dir = os.path.join(kvdir, str(step))
        try:
            with open(os.path.join(step_dir, META_NAME)) as f:
                meta = json.load(f)
        except Exception:  # noqa: BLE001 — torn snapshot: fall back
            continue
        # A format mismatch is a code/snapshot version skew, not a torn
        # write — raise it instead of silently walking past (the
        # fallback would otherwise resume from a stale snapshot or fail
        # later with an unrelated journal-only error).
        if meta.get("format") != SNAPSHOT_FORMAT:
            raise ValueError(
                f"snapshot {step_dir} has format {meta.get('format')}; "
                f"this build reads format {SNAPSHOT_FORMAT}")
        mc = meta.get(META_CRC)
        if mc is not None and int(mc) != canonical_crc(
                meta, exclude=(META_CRC,)):
            raise SnapshotCorrupt(
                f"snapshot {step_dir}: meta.json self-digest mismatch "
                f"— refusing to adopt; quarantine the step "
                f"(scripts/serve_fsck.py --salvage) to restore from an "
                f"older snapshot + the journal")
        try:
            like = _abstract_pool_tree(meta)
            pools = ck.restore(step_dir, like)
        except Exception:  # noqa: BLE001 — torn snapshot: fall back
            continue
        digs = meta.get("digests")
        if digs is None:
            print(f"[recovery] snapshot {step_dir} predates leaf "
                  f"digests: restoring unverified", file=sys.stderr)
        else:
            for name in sorted(like):
                got = crc32_bytes(np.ascontiguousarray(
                    np.asarray(pools[name])).tobytes())
                want = digs.get(name)
                if want is None or int(want) != got:
                    raise SnapshotCorrupt(
                        f"snapshot {step_dir}: pool leaf {name!r} "
                        f"digest mismatch (recorded {want}, stored "
                        f"{got}) — refusing to adopt corrupt KV; "
                        f"quarantine the step (scripts/serve_fsck.py "
                        f"--salvage) to restore from an older "
                        f"snapshot + the journal")
        return step, meta, pools
    return None


# ---------------------------------------------------------------------------
# Restore
# ---------------------------------------------------------------------------


def _resolve_callback(on_token, rid: str) -> Optional[Callable]:
    if on_token is None:
        return None
    if callable(on_token):
        return on_token
    return on_token.get(rid)


def _shift(ts: Optional[float], offset: float) -> Optional[float]:
    return None if ts is None else ts + offset


_META_KW = ("num_blocks", "page_size", "max_batch", "prefill_chunk",
            "prefill_budget", "horizon", "pipeline", "snapshot_every",
            "prefix_cache", "spec_fused")


def restore_engine(directory: str | os.PathLike, gen, params, *,
                   draft=None, draft_params=None,
                   clock=time.monotonic,
                   on_token: Union[None, Callable, dict] = None,
                   replay_tokens: bool = False,
                   faults=None, journal_fsync: bool = False,
                   journal_fsync_interval_s: Optional[float] = None,
                   journal_rotate_bytes: Optional[int] = None,
                   **overrides):
    """Rebuild a :class:`ServeEngine` from the snapshot + journal under
    ``directory`` (the implementation of ``ServeEngine.restore``).

    ``gen``/``params`` (and ``draft``/``draft_params`` for speculative
    engines) are the caller's — model weights are not snapshotted, like
    any serving deployment they come from the model store.  Engine
    geometry defaults to the snapshot manifest's; any ``overrides``
    (``num_blocks=``, ``max_batch=``, ``horizon=``, ...) win, and
    requests that no longer fit the overridden geometry re-queue through
    admission and recompute (streams stay bit-exact — see the module
    docstring).  ``on_token`` re-attaches streaming callbacks (one
    callable for all requests, or a ``{rid: callable}`` map);
    ``replay_tokens=True`` re-fires them for every journaled token
    (at-least-once delivery for the crash-window token instead of the
    default at-most-once).
    """
    from triton_dist_tpu.serve.engine import ServeEngine

    directory = os.path.abspath(os.fspath(directory))
    snap = _load_latest_snapshot(directory)
    # Salvage, don't just replay: interior journal corruption quarantines
    # the damaged file and resumes from the records that still verify —
    # the snapshot manifest + fleet delivery record reconcile anything
    # the salvage lost (see the merge below and fleet._absorb_manifest).
    journal, jdamage = salvage_journal(os.path.join(directory, JOURNAL_NAME))
    if snap is None and not journal:
        raise FileNotFoundError(
            f"no restorable snapshot or journal under {directory}")
    step, meta, pools_raw = snap if snap is not None else (None, None, None)

    kw: dict[str, Any] = {}
    if meta is not None:
        for k in _META_KW:
            if k in meta["engine"]:  # tolerate pre-prefix-cache manifests
                kw[k] = meta["engine"][k]
        if draft is not None:
            kw["spec_k"] = meta["engine"]["spec_k"]
    kw.update(overrides)
    if "num_blocks" not in kw or "page_size" not in kw:
        raise ValueError(
            "journal-only restore (no KV snapshot) needs explicit engine "
            "geometry: pass num_blocks=, page_size=, ... as overrides")
    snap_every = kw.pop("snapshot_every", None)
    if snap_every is not None and snap_every < 1:
        raise ValueError(f"snapshot_every must be >= 1, got {snap_every}")
    # Constructed journal-less, then wired by hand: the engine refuses
    # a populated snapshot_dir at construction (a FRESH life appending
    # there would corrupt replay) — restore is the one sanctioned way
    # to reopen it.
    engine = ServeEngine(gen, params, draft=draft,
                         draft_params=draft_params, clock=clock,
                         faults=faults, **kw)
    engine.snapshot_dir = directory
    engine.snapshot_every = snap_every
    engine.journal_fsync_interval_s = journal_fsync_interval_s
    engine.journal_rotate_bytes = journal_rotate_bytes
    engine._journal = TokenJournal(
        os.path.join(directory, JOURNAL_NAME), fsync=journal_fsync,
        fsync_interval_s=journal_fsync_interval_s, faults=faults)
    if meta is not None:
        engine._snap_seq = step + 1
        engine._spec_off = bool(meta.get("spec_off", False))

    # -- pools: reusable iff the per-page geometry survived ---------------
    pools_ok = False
    if pools_raw is not None:
        e = meta["engine"]
        cfg = engine.cfg
        # Pool quantization mismatches are LOUD, not a silent requeue:
        # adopting fp bytes into int8 pools (or vice versa) would need a
        # quantization pass that cannot be bit-exact, and silently
        # recomputing every request would mask a deployment error (the
        # operator pointed a differently-configured engine at live
        # state).  Cross-dtype moves go through drain/migrate requeue by
        # design; restore demands the same engine class.
        if bool(e.get("kv_quant", False)) != engine.kv_quant:
            raise ValueError(
                f"snapshot under {directory} holds "
                f"{'int8-quantized' if e.get('kv_quant') else 'float'} "
                f"KV pools but the restoring engine allocates "
                f"{'int8-quantized' if engine.kv_quant else 'float'} "
                f"pools (Generator kv_dtype mismatch) — restore with a "
                f"matching kv_dtype, or migrate the requests through a "
                f"drain manifest (cross-dtype adoption requeues for "
                f"exact recompute)")
        same_geom = (e["page_size"] == engine.page
                     and e["n_layers"] == cfg.n_layers
                     and e["n_kv_heads"] == cfg.n_kv_heads
                     and e["head_dim"] == cfg.head_dim
                     and e["kv_dtype"] == str(np.dtype(cfg.dtype)))
        if same_geom:
            import jax.numpy as jnp

            n_copy = min(e["num_blocks"], engine.bm.num_blocks)

            def adopt(cur, saved):
                if saved.shape == cur.shape:
                    return jnp.asarray(saved)
                # Different block count: the overlapping pool rows
                # carry over; requests whose tables reach past them
                # recompute instead of resuming in place.
                return cur.at[:n_copy].set(jnp.asarray(saved)[:n_copy])

            new_pools = []
            for i, (k, v) in enumerate(engine._pools):
                if engine.kv_quant:
                    new_pools.append(
                        ({"q": adopt(k["q"], pools_raw[f"l{i}_k_q"]),
                          "s": adopt(k["s"], pools_raw[f"l{i}_k_s"])},
                         {"q": adopt(v["q"], pools_raw[f"l{i}_v_q"]),
                          "s": adopt(v["s"], pools_raw[f"l{i}_v_s"])}))
                else:
                    new_pools.append(
                        (adopt(k, pools_raw[f"l{i}_k"]),
                         adopt(v, pools_raw[f"l{i}_v"])))
            # One device_put per leaf lays the (global) restored pools
            # out on the restoring engine's mesh — restore across mesh
            # shapes is exactly this re-layout (no-op off-mesh).
            engine._pools = engine._place_pools(new_pools)
            pools_ok = True

    # -- spec device state: draft caches + round-opening logits -----------
    # Restorable iff the snapshot carried it AND the caller's draft has
    # the exact geometry (the draft caches are slot-indexed [max_batch]
    # arrays, so max_batch must match too).  Without it, spec rows
    # requeue through the exact-recompute path — bit-exact either way.
    spec_ok = False
    if (pools_ok and engine.spec_k and not engine._spec_off
            and meta["engine"].get("spec_k") == engine.spec_k
            and meta["engine"].get("max_batch") == engine.max_batch
            and meta["engine"].get("draft")
            and "draft_kv_lens" in pools_raw):
        from triton_dist_tpu.models.generate import GenerationState

        d = meta["engine"]["draft"]
        dcfg = engine.draft.cfg
        if (d["n_layers"] == dcfg.n_layers
                and d["n_kv_heads"] == dcfg.n_kv_heads
                and d["head_dim"] == dcfg.head_dim
                and d["max_seq"] == engine.draft.max_seq
                and d["vocab"] == dcfg.vocab
                and d["dtype"] == str(np.dtype(dcfg.dtype))):
            import jax.numpy as jnp

            engine._draft_state = GenerationState(
                caches=[(jnp.asarray(pools_raw[f"d{i}_k"]),
                         jnp.asarray(pools_raw[f"d{i}_v"]))
                        for i in range(d["n_layers"])],
                kv_lens=jnp.asarray(pools_raw["draft_kv_lens"]),
                last_logits=jnp.asarray(
                    pools_raw["draft_last_logits"]))
            engine._last_logits = jnp.asarray(
                pools_raw["spec_last_logits"])
            spec_ok = True

    # -- merge journal over manifest --------------------------------------
    m_reqs = meta["requests"] if meta is not None else {}
    m_outs = meta["outputs"] if meta is not None else {}
    m_tables = meta["tables"] if meta is not None else {}

    resolved: dict[str, dict] = {}
    order: list[str] = []

    def slot_for(rid) -> dict:
        if rid not in resolved:
            resolved[rid] = {"rid": rid}
            order.append(rid)
        return resolved[rid]

    for rid in list(m_reqs) + [r for r in m_outs if r not in m_reqs]:
        r = slot_for(rid)
        src = m_reqs.get(rid) or m_outs[rid]
        r["prompt"] = np.asarray(src["prompt"], np.int32)
        r["params"] = (SamplingParams.from_dict(src["params"])
                       if "params" in src else SamplingParams())
        r["arrival"] = src.get("arrival")
        r["slo"] = src.get("slo", "interactive")
        if rid in m_reqs:
            r["tokens"] = list(m_reqs[rid]["gen"])
            r["tok_ts"] = list(m_reqs[rid].get("token_times", []))
        else:
            r["tokens"] = list(m_outs[rid]["tokens"])
            r["tok_ts"] = []
        if rid in m_outs:
            r["finish"] = {"reason": m_outs[rid]["reason"],
                           "err": m_outs[rid]["error"], "ts": None}
    for rid, jr in journal.items():
        r = slot_for(rid)
        if jr.prompt is not None:
            r.setdefault("prompt", jr.prompt)
            r.setdefault("params", jr.params)
            r.setdefault("arrival", jr.arrival)
            r.setdefault("slo", jr.slo)
        toks = jr.token_list()
        # The journal syncs before every snapshot, so it is a superset
        # of the manifest's token view — prefer it whenever longer (the
        # journal-ahead suffix is what recompute replays).
        if len(toks) >= len(r.get("tokens", [])):
            r["tokens"] = toks
            r["tok_ts"] = jr.token_times()
        if jr.first_tok is not None:
            r.setdefault("first_tok", jr.first_tok)
        if jr.trace is not None:
            r.setdefault("trace", jr.trace)
        if jr.finish is not None:
            r["finish"] = jr.finish
    # A rid only ever seen as a finish/token record (its submit line was
    # torn away with the crash) cannot be rebuilt — drop it.  A rid the
    # journal marks MIGRATED is owned by another replica now (its "mig"
    # record is the hand-off receipt — docs/serving.md "Fleet serving"):
    # resurrecting it here would double-serve the stream, even when a
    # pre-drain KV snapshot still lists it, so it is dropped outright
    # (the target replica's journal carries its past and its future).
    order = [rid for rid in order
             if resolved[rid].get("prompt") is not None
             and not (rid in journal and journal[rid].migrated)]

    if meta is not None:
        old_now = meta["clock"]
    else:
        # Journal-only restore: the newest old-clock timestamp anywhere
        # in the journal (token commit, submit, or finish) stands in for
        # the snapshot clock.  Token times alone are not enough — a kill
        # before the first commit would leave old_now at 0, pushing every
        # re-based arrival into the future and deadline TTLs with it.
        old_now = max(
            [ts for jr in journal.values()
             for _, ts in jr.tokens.values() if ts is not None] +
            [jr.arrival for jr in journal.values()
             if jr.arrival is not None] +
            [jr.finish["ts"] for jr in journal.values()
             if jr.finish is not None and jr.finish.get("ts") is not None],
            default=0.0)
    offset = engine._clock() - (old_now or 0.0)

    # -- rebuild finished requests (accounting only; never re-queued) -----
    m = engine.metrics

    def finish_restored(rid: str, reason: FinishReason,
                        finish_ts: Optional[float],
                        err: Optional[str] = None) -> ReqState:
        # Every timestamp lands on the new clock base (shifted by
        # offset, like build_state's live rows) so restored durations
        # never mix clock lives.
        r = resolved[rid]
        rm = RequestMetrics(
            arrival_time=_shift(r["arrival"], offset) or 0.0)
        # explicit first-token stamp BEFORE seeding: a rotated journal's
        # tts None-pads its head past the bounded window, and seeding
        # from the first RETAINED stamp would inflate the restored TTFT
        # by the whole decode (seed_token_times only fills a None)
        rm.first_token_time = _shift(r.get("first_tok"), offset)
        rm.seed_token_times(
            [_shift(t, offset) for t in (r.get("tok_ts") or [])],
            total=len(r["tokens"]))
        rm.finish_time = finish_ts
        req = Request(rid, r["prompt"], r["params"],
                      arrival_time=rm.arrival_time,
                      slo_class=r.get("slo", "interactive"))
        rs = ReqState(req=req, metrics=rm, status=Status.FINISHED)
        rs.generated = list(r["tokens"])
        out = RequestOutput(request_id=rid, prompt=req.prompt,
                            token_ids=list(r["tokens"]),
                            finish_reason=reason, metrics=rm, error=err)
        engine._states[rid] = rs
        engine._outputs[rid] = out
        m.observe_finish(rid, rm, reason, slo_class=req.slo_class)
        return rs

    inflight: list[str] = []
    for rid in order:
        r = resolved[rid]
        if r.get("finish") is None:
            inflight.append(rid)
            continue
        reason = FinishReason(r["finish"]["reason"])
        finish_restored(rid, reason, _shift(r["finish"].get("ts"), offset),
                        err=r["finish"].get("err"))
        if reason is FinishReason.SHED:
            m.shed += 1
        elif reason is FinishReason.DEADLINE:
            m.deadline_expired += 1
        elif reason is FinishReason.ERROR:
            m.quarantined += 1

    # -- close the commit→retire crash window -----------------------------
    # A kill can land after a token's journal append but before the
    # retire that token triggers (its EOS, or the max_new_tokens
    # boundary).  The journal then shows a COMPLETE stream with no fin
    # record; re-queueing it would generate past the request's budget.
    # Finish it here — bit-identical to the retire the crash swallowed.
    def stream_done(rid: str) -> Optional[FinishReason]:
        r = resolved[rid]
        p = r["params"]
        if (p.eos_id is not None and r["tokens"]
                and r["tokens"][-1] == p.eos_id):
            return FinishReason.EOS
        if len(r["tokens"]) >= p.max_new_tokens:
            return FinishReason.LENGTH
        return None

    still = []
    window_finished: list[str] = []
    for rid in inflight:
        reason = stream_done(rid)
        if reason is None:
            still.append(rid)
            continue
        rs = finish_restored(rid, reason, engine._clock())
        m.restored_tokens += len(rs.generated)
        window_finished.append(rid)
        # the swallowed retire's fin record lands via the journal
        # backfill below (the single fin writer at restore)
    inflight = still

    # -- classify in-flight requests: resume in place vs recompute --------
    # A RUNNING row resumes in place iff its snapshot invariant matches
    # how THIS engine will serve it.  Plain serving needs the pending
    # token (kv_len rows + one emitted-but-unconsumed token); fused spec
    # serving has no pending token — its round state is the snapshotted
    # draft caches + logits rows (``spec_ok``), which are SLOT-indexed,
    # so the row must come back in its original slot.  Rows from a spec
    # snapshot restored into a plain (or draft-less) engine fail the
    # pending check and requeue through exact recompute — bit-exact
    # either way.
    spec_live = bool(engine.spec_k) and not engine._spec_off

    def resumable(rid: str) -> bool:
        mr = m_reqs.get(rid)
        if not (pools_ok and mr is not None
                and mr["status"] == Status.RUNNING.value):
            return False
        if spec_live:
            if not spec_ok or mr["pending"] is not None \
                    or mr.get("slot") is None:
                return False
        elif mr["pending"] is None:
            return False
        r = resolved[rid]
        if len(r["tokens"]) != len(mr["gen"]):
            return False  # journal ran ahead of the KV snapshot
        table = m_tables.get(rid)
        if table is None or len(table) > engine.n_pages_max:
            return False
        if any(b >= engine.bm.num_blocks for b in table):
            return False  # shrunk pool: those rows don't exist any more
        if not engine.bm.placement_ok(table):
            # A table snapshotted under a different mesh shape
            # (kv_shard='seq' partitions moved): the pages' bytes are
            # in the restored pools but in the WRONG ranks' partitions
            # — recompute, exactly like a shrunk-geometry restore.
            return False
        total = int(r["prompt"].shape[0]) + r["params"].max_new_tokens
        return total <= engine.gen.max_seq

    resume = [rid for rid in inflight if resumable(rid)]
    resume.sort(key=lambda rid: m_reqs[rid]["seq"])
    resume_set = set(resume)
    requeue = [rid for rid in inflight if rid not in resume_set]
    # Re-queue order: previously admitted rows first (admission order),
    # then the old waiting line, then post-snapshot journal-only
    # arrivals in submit order — FCFS fairness survives the crash.
    requeue_set = set(requeue)
    admitted = sorted((rid for rid in requeue if rid in m_reqs
                       and m_reqs[rid]["status"] != Status.WAITING.value),
                      key=lambda rid: m_reqs[rid]["seq"])
    waiting = [rid for rid in meta["waiting"] if rid in requeue_set] \
        if meta is not None else []
    placed = set(admitted) | set(waiting)
    rest = [rid for rid in requeue if rid not in placed]
    requeue = admitted + waiting + rest

    free_slots = [i for i in range(engine.max_batch)]

    def build_state(rid: str) -> ReqState:
        r = resolved[rid]
        mr = m_reqs.get(rid, {})
        rm = RequestMetrics(
            arrival_time=_shift(r["arrival"], offset) or engine._clock())
        rm.first_scheduled_time = _shift(mr.get("first_sched"), offset)
        ft = mr.get("first_tok")
        if ft is None:
            ft = r.get("first_tok")   # rotated-journal "ftt" record
        rm.first_token_time = _shift(ft, offset)
        rm.seed_token_times(
            [_shift(t, offset) for t in (r.get("tok_ts") or [])],
            total=len(r["tokens"]))
        rm.n_preemptions = mr.get("n_preempt", 0)
        req = Request(rid, r["prompt"], r["params"],
                      arrival_time=rm.arrival_time,
                      on_token=_resolve_callback(on_token, rid),
                      slo_class=r.get("slo", "interactive"),
                      trace=r.get("trace")
                      or {"trace_id": rid, "hop": 0})
        rs = ReqState(req=req, metrics=rm)
        rs.generated = list(r["tokens"])
        rs.journal_base = len(rs.generated)
        rs.callback_disabled = bool(mr.get("cb_off", False))
        # a restore is the SAME life continuing (same replica, same
        # journal dir): the journey keeps its hop — only a migration
        # to another replica bumps it
        engine._trace_ctx[rid] = req.trace
        return rs

    resumed: list[str] = []
    for rid in resume:
        mr = m_reqs[rid]
        if spec_live:
            # The draft caches/logits rows are slot-indexed: a spec row
            # resumes in ITS slot or not at all.
            slot = mr["slot"] if mr["slot"] in free_slots else None
        else:
            slot = mr["slot"] if mr["slot"] in free_slots else (
                free_slots[0] if free_slots else None)
        if slot is None:  # geometry shrank under us: recompute instead
            requeue.insert(0, rid)
            continue
        free_slots.remove(slot)
        rs = build_state(rid)
        # shared_ok under the prefix cache: snapshot tables legitimately
        # overlap on shared prefix blocks (refcounts rebuild from the
        # overlap itself); without it, overlap still means corruption.
        engine.bm.adopt(rid, m_tables[rid],
                        shared_ok=engine.bm.prefix_cache)
        rs.status = Status.RUNNING
        rs.slot = slot
        rs.kv_len = mr["kv_len"]
        rs.pending_token = mr["pending"]
        rs.seq = mr["seq"]
        rs.cached_prefix = mr.get("cached_prefix", 0)
        rs.committed_pages = mr.get("committed_pages", 0)
        rs.metrics.cached_prefix_tokens = rs.cached_prefix
        engine.slots[slot] = rs
        engine._states[rid] = rs
        resumed.append(rid)
        m.restored_in_place += 1
        m.restored_tokens += len(rs.generated)

    for rid in requeue:
        r = resolved[rid]
        total = int(r["prompt"].shape[0]) + r["params"].max_new_tokens
        rs = build_state(rid)
        if (total > engine.gen.max_seq
                or engine.bm.fit_error(total) is not None):
            # The restored geometry can NEVER serve this request; parking
            # it in the queue would wedge FCFS admission forever.
            rs.status = Status.FINISHED
            msg = (f"restored engine cannot serve {total} tokens "
                   f"(max_seq {engine.gen.max_seq}, "
                   f"{engine.bm.num_allocatable} allocatable blocks)")
            rm2 = rs.metrics
            rm2.finish_time = engine._clock()
            out = RequestOutput(request_id=rid, prompt=rs.req.prompt,
                                token_ids=list(rs.generated),
                                finish_reason=FinishReason.ERROR,
                                metrics=rm2, error=msg)
            engine._states[rid] = rs
            engine._outputs[rid] = out
            m.quarantined += 1
            m.observe_finish(rid, rm2, FinishReason.ERROR)
            # fin record lands via the backfill below; its tokens were
            # NOT carried anywhere, so restored_tokens excludes them
            continue
        if rs.generated:
            rs.work_prompt = np.concatenate(
                [rs.req.prompt, np.asarray(rs.generated, np.int32)])
        rs.status = Status.WAITING
        engine._states[rid] = rs
        engine.scheduler.add(rs)
        m.restored_requeued += 1
        m.restored_tokens += len(rs.generated)

    # -- prefix cache: index + warm tier survive the restart --------------
    # Live shared blocks re-register first (their tables were just
    # re-adopted), then the snapshot's LRU cache tier re-admits in order
    # — restore's adopt path doubling as cache admission, so a restarted
    # engine's first warm prompt still skips its prefill.  Gated on
    # pools_ok: without the restored pool bytes a "warm" block would
    # certify KV that no longer exists.
    pfx = meta.get("prefix") if meta is not None else None
    if pfx and pools_ok and engine.bm.prefix_cache:
        n_valid = min(meta["engine"]["num_blocks"], engine.bm.num_blocks)
        index = [(int(b), int(p), t) for b, p, t in pfx.get("index", ())
                 if 0 < int(b) < n_valid]
        engine.bm.restore_index(index)
        by_block = {b: (p, t) for b, p, t in index}
        for b in pfx.get("cached", ()):
            if int(b) in by_block:
                p, t = by_block[int(b)]
                engine.bm.admit_cached(int(b), p, t)

    seqs = [s.seq for s in engine.slots if s is not None]
    engine.scheduler._seq = max(
        [meta["seq_counter"] if meta is not None else 0] +
        [s + 1 for s in seqs])

    # -- journal backfill: keep the journal self-contained ----------------
    # A restored engine appends future commits at index journal_base;
    # when the state came from a manifest the journal never saw (a
    # snapshot taken by an engine without a journal, or a journal lost
    # with its disk), those earlier indices would be a GAP — and a
    # second crash would replay a truncated stream.  Backfill the
    # missing submit/token/finish records now, so every life leaves a
    # journal any later restore can trust on its own.
    if engine._journal is not None:
        for rid, rs in engine._states.items():
            jr = journal.get(rid)
            if jr is None or jr.prompt is None:
                engine._journal.submit(rs.req)
            have = len(jr.token_list()) if jr is not None else 0
            for i in range(have, len(rs.generated)):
                ts = rs.metrics.time_at(i)
                engine._journal.token(rid, i, rs.generated[i],
                                      engine._clock() if ts is None
                                      else ts)
            if (rs.status is Status.FINISHED
                    and (jr is None or jr.finish is None)):
                out = engine._outputs[rid]
                engine._journal.finish(
                    rid, out.finish_reason.value, out.error,
                    len(out.token_ids),
                    rs.metrics.finish_time or engine._clock())
        engine._note_journal()

    if replay_tokens and on_token is not None:
        for rid in resumed + requeue:
            rs = engine._states[rid]
            cb = rs.req.on_token
            if (cb is None or rs.callback_disabled
                    or rs.status is Status.FINISHED):
                continue  # finished-at-restore rows don't re-stream
            for tok in rs.generated[:rs.journal_base]:
                cb(rid, tok)
        # A stream that completed exactly at the crash (fin record
        # swallowed) still owes its in-flight callback — a fin record
        # on disk proves the pre-crash retire (and with it every
        # callback) ran, its absence proves nothing.  Re-fire the whole
        # journaled stream: same at-least-once contract as live rows.
        for rid in window_finished:
            cb = _resolve_callback(on_token, rid)
            if cb is None or m_reqs.get(rid, {}).get("cb_off", False):
                continue
            for tok in engine._states[rid].generated:
                cb(rid, tok)

    # -- flight-recorder provenance ---------------------------------------
    # The snapshot's ring tail seeds the restored recorder (the previous
    # life's trail precedes this life's events), and the restore itself
    # is an event: a later postmortem shows the lineage.
    if meta is not None and meta.get("flight"):
        engine.trace.seed(meta["flight"])
    if jdamage is not None:
        m.journal_corrupt += 1
        engine.trace.emit("corrupt", None, artifact="journal",
                          **jdamage.summary())
    engine.trace.emit("restore", None, in_place=m.restored_in_place,
                      requeued=m.restored_requeued,
                      tokens=m.restored_tokens)
    m.restores += 1
    return engine


# ---------------------------------------------------------------------------
# Live migration: journal-segment hand-off between replicas
# ---------------------------------------------------------------------------
#
# A migration MANIFEST is the unit of request hand-off between engine
# replicas (docs/serving.md "Fleet serving").  It carries, per request,
# everything a target ``ServeEngine.migrate_in`` needs to continue the
# stream exactly-once: prompt, sampling params (the per-token PRNG
# stream), the journaled token prefix with timestamps, and — on the
# cooperative ``ServeEngine.drain`` path — the live KV pages + pending
# token so the target resumes mid-stream with zero recompute.  Two
# producers exist:
#
# - ``ServeEngine.drain(rids)`` on a LIVE source: the engine gathers the
#   per-request KV pages, journals a ``mig`` record per request (the
#   ownership receipt), and frees its own state.
# - :func:`manifest_from_journal` on a DEAD replica's directory: the
#   durable journal is the source of truth for what was emitted, so the
#   manifest is exact even though the process is gone (no KV rides —
#   the target replays through the exact-recompute path, bit-identical
#   by the PR 5 argument).  ``mark=True`` appends the ``mig`` receipts
#   to the dead journal so a later ``--resume`` of that directory can
#   never resurrect the handed-off requests.

MANIFEST_FORMAT = 1


def manifest_from_journal(directory: str | os.PathLike, *,
                          mark: bool = False) -> dict:
    """Build a migration manifest for every UNFINISHED, un-migrated
    request in ``directory``'s token journal (the crash-path producer —
    the replica is dead, its journal is what survives).

    Returns ``{"format", "clock", "requests": [...], "finished": [...]}``
    where ``finished`` lists requests whose ``fin`` record landed but
    whose output the fleet controller may not have collected (the dying
    step's retirements) — accounting, never re-served.  ``mark=True``
    appends a ``mig`` record per handed-off request (safe only once the
    source process is dead: two writers on one journal corrupt it).

    Trace continuity on the crash path: each record carries the
    journal's trace context, and — when the dying step managed its
    ``force=True`` flight flush (it does on anything escaping,
    ``InjectedKill`` included) — the request's ring-event tail recovered
    from the newest ``flight_*.json``, so the adopting replica's ring
    and the merged fleet timeline show the dead life's events too
    (docs/observability.md "Fleet observability").
    """
    from triton_dist_tpu.serve.trace import (
        MIGRATE_EVENT_TAIL,
        latest_flight,
        load_flight,
    )

    directory = os.path.abspath(os.fspath(directory))
    if not os.path.exists(os.path.join(directory, JOURNAL_NAME)):
        # A replica that died during init (subprocess spawn, model
        # build) never opened a journal: it owned nothing, so the
        # hand-off is empty — not an error (the network fleet hits
        # this when a child is killed before the engine exists).
        return {"format": MANIFEST_FORMAT, "clock": 0.0,
                "requests": [], "finished": []}
    # The replica is already dead — corruption here must not kill the
    # crash path too.  Salvage the longest-valid prefix and carry the
    # damage report in the manifest so the controller can reconcile the
    # lost tail against its delivery record (fleet._absorb_manifest).
    journal, jdamage = salvage_journal(os.path.join(directory, JOURNAL_NAME))
    # per-rid event tails from the dead life's postmortem flush (best
    # effort: a SIGKILL with no flush just means no carried events)
    tails: dict[str, list] = {}
    fl = latest_flight(directory)
    if fl is not None:
        try:
            for ev in load_flight(fl).get("events", ()):
                ts, step, etype, rid, data = ev
                if rid is not None:
                    tails.setdefault(rid, []).append(
                        [ts, step, etype, data])
        except (OSError, ValueError, json.JSONDecodeError):
            tails = {}
    # Clock re-base (the restore_engine rule): the newest source-clock
    # stamp anywhere in the journal stands in for "now" on the source.
    old_now = max(
        [ts for jr in journal.values()
         for _, ts in jr.tokens.values() if ts is not None] +
        [jr.arrival for jr in journal.values() if jr.arrival is not None] +
        [jr.finish["ts"] for jr in journal.values()
         if jr.finish is not None and jr.finish.get("ts") is not None],
        default=0.0)
    reqs, finished, handed = [], [], []
    for rid, jr in journal.items():
        if jr.prompt is None or jr.migrated:
            continue
        toks = jr.token_list()
        if jr.finish is not None:
            finished.append({
                "rid": rid,
                "prompt": [int(x) for x in jr.prompt],
                "tokens": toks,
                "reason": jr.finish["reason"],
                "err": jr.finish.get("err"),
            })
            continue
        reqs.append({
            "rid": rid,
            "prompt": [int(x) for x in jr.prompt],
            "params": jr.params.to_dict(),
            "arrival": jr.arrival,
            "slo": jr.slo,
            "tokens": toks,
            "tok_ts": jr.token_times(),
            "first_tok": jr.first_tok,
            "trace": jr.trace or {"trace_id": rid, "hop": 0},
            "events": tails.get(rid, [])[-MIGRATE_EVENT_TAIL:],
        })
        handed.append((rid, len(toks)))
    if mark and handed:
        j = TokenJournal(os.path.join(directory, JOURNAL_NAME))
        try:
            for rid, n in handed:
                j.migrate(rid, n, old_now)
            j.sync()
        finally:
            j.close()
    out = {"format": MANIFEST_FORMAT, "clock": old_now,
           "requests": reqs, "finished": finished}
    if jdamage is not None:
        out["damage"] = jdamage.summary()
    return out


def save_manifest(manifest: dict, path: str | os.PathLike) -> str:
    """Write a manifest as JSON (atomic tmp + rename + whole-document
    digest, via :func:`integrity.atomic_write_json`) — the subprocess
    hand-off format (``examples/serve.py --migrate-in``).  KV payloads
    are dropped: the JSON manifest is the journal-segment crash path,
    and the target replays through exact recompute."""
    path = os.path.abspath(os.fspath(path))
    doc = dict(manifest)
    doc["requests"] = [{k: v for k, v in r.items() if k not in
                        ("kv", "kv_len", "pending", "s_ext")}
                       for r in manifest.get("requests", [])]
    return atomic_write_json(path, doc)


def load_manifest(path: str | os.PathLike) -> dict:
    with open(path, encoding="utf-8") as f:
        m = json.load(f)
    if m.get("format") != MANIFEST_FORMAT:
        raise ValueError(f"manifest {path} has format {m.get('format')}; "
                         f"this build reads format {MANIFEST_FORMAT}")
    # Pre-integrity manifests carry no digest (tri-state None passes).
    if verify_json_doc(m) is False:
        raise ValueError(
            f"manifest {path}: whole-document digest mismatch — the "
            f"file is corrupt; regenerate it from the source journal "
            f"(manifest_from_journal) or scripts/serve_fsck.py")
    m.pop(DOC_CRC, None)
    return m
