"""Mesh placement for :class:`serve.engine.ServeEngine` — TP weights +
sharded paged KV under ``shard_map`` (docs/serving.md "Sharded serving").

The engine's device programs (paged decode, multi-token verify, the
fused decode horizon, chunked prefill, the page scatter/gather/COW
trio, the fused speculative round) are all parameterized over cache
addressing and the two weight-reduction seams (``generate._token_forward``
/ ``_multitoken_forward`` / ``_chunk_forward``'s ``write_kv`` /
``attend`` / ``ffn`` / ``out_proj`` hooks) — this module instantiates
them PER-SHARD and wraps each in ``jax.jit(jax.shard_map(...))`` so the
same engine step loop, scheduler, and block tables drive a multi-chip
forward.  Two KV layouts:

- ``kv_shard="heads"`` — Megatron-style tensor parallelism: weights
  shard by ``models.llama.param_specs`` (QKV/up-gate column-parallel,
  attn-out/down row-parallel + ``psum``), the paged pools shard on the
  KV-head axis, and each rank runs ``gqa_decode_paged_shard`` over its
  own heads (attention is head-independent, so no inter-rank combine
  exists on the attention path).  Supports everything the world-1
  engine does, speculative rounds included (the draft model runs
  replicated per rank — its batch caches are slot-indexed host-managed
  state that must stay whole on every rank).
- ``kv_shard="seq"`` — SP flash-decode (the reference's headline 1→32
  scaling, SURVEY.md §5): pools shard on the BLOCK axis, each rank
  holds the pages of its contiguous sequence span, attention goes
  through ``sp_gqa_decode_paged_shard`` (per-rank local lengths + the
  LSE combine) with the rank's slice of the block table rebased to
  local pool rows.  Weights stay replicated (the decode-serving layout
  of models/generate.py: the sharded thing is the KV cache).  Since
  ISSUE 19 the layout is first-class: the paged SP combine merges
  queries×heads 4D partials, so multi-token verify — and therefore
  speculative decode — runs under seq, and chunked prefill attends
  over the rank-local slice of the scratch (per-shard partials + the
  same LSE combine) instead of computing replicated.
- ``kv_shard="heads+seq"`` — the 2D composition (ISSUE 19): one
  ``Mesh((tp, sp))`` where weights and attention heads shard on the
  ``tp`` axis (psum only at the out-proj/FFN row-parallel seams,
  exactly the heads layout) while the paged pools and the partitioned
  BlockManager shard on the block axis over ``sp`` (partition count =
  sp world, NOT total world).  Every per-shard body is the seq body
  with the TP seams threaded through (``fwd_cfg``/``ffn``/
  ``out_proj``), so attention runs per-rank over (local heads × local
  blocks) and combines on ``sp`` only — KV capacity (sp) and per-step
  latency (tp) scale on independent axes.

**The executable-cache fork (the PR-7 problem, solved here).**  A
mesh-placed program's outputs carry ``NamedSharding`` while host-built
arrays carry single-device placements, and jax's jit cache keys on the
argument shardings — so one traced program would split into host-built
vs device-carried executable flavors that ``warmup()`` cannot
enumerate (the compile-miss counter would tick under traffic).
:class:`ShardedProgram` therefore CANONICALIZES every argument at the
call seam: each arg is ``device_put`` onto its declared
``NamedSharding`` unless it already carries it, so every call of a
program presents ONE signature and the cache holds exactly one
executable per (shapes, statics) — ``warmup()`` reaches the same
compile fixed point as world-1 and the miss counter stays flat.

Bit-exactness note: per-head attention, column-parallel projections and
the replicated sampling/commit path are arithmetically identical to
world-1; the row-parallel ``psum`` seams reduce in shard-major order,
which the oracle tests pin stream-exact on the test models (the same
standard tests/test_generate.py holds the SP combine to at world 4).
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.kernels.flash_decode import (
    sp_gqa_decode_paged_shard,
    sp_gqa_decode_shard,
)
from triton_dist_tpu.models.generate import (
    _chunk_forward,
    _multitoken_forward,
    _token_forward,
)
from triton_dist_tpu.models.llama import param_specs
from triton_dist_tpu.runtime import jit_cache


# ---------------------------------------------------------------------------
# Geometry validation — the loud construction-time rejection matrix
# ---------------------------------------------------------------------------


KV_SHARDS = ("heads", "seq", "heads+seq")


def _check_heads_geometry(cfg, world, kv_shard, label):
    """The heads-TP divisibility rules, parameterized over the axis
    label so a 2D rejection names WHICH axis failed."""
    if cfg.n_kv_heads % world:
        raise ValueError(
            f"kv_shard={kv_shard!r} needs n_kv_heads ({cfg.n_kv_heads}) "
            f"divisible by the {label} ({world}) — each rank "
            f"must own whole KV heads of the paged pools")
    if cfg.n_heads % world:
        raise ValueError(
            f"kv_shard={kv_shard!r} needs n_heads ({cfg.n_heads}) "
            f"divisible by the {label} ({world}) — the "
            f"column-parallel QKV split assigns whole query heads "
            f"per rank")
    if cfg.ffn_dim % world:
        raise ValueError(
            f"TP weights need ffn_dim ({cfg.ffn_dim}) divisible by "
            f"the {label} ({world}) — wgate/wup shard by "
            f"columns, wdown by rows")


def _check_seq_geometry(max_seq, num_blocks, page_size, world, kv_shard,
                        label):
    """The seq-SP divisibility rules, axis-labeled like the heads
    twin."""
    n_pages = max_seq // page_size
    if n_pages % world:
        raise ValueError(
            f"kv_shard={kv_shard!r} needs max_seq/page_size ({n_pages} "
            f"logical pages) divisible by the {label} ({world}) "
            f"— each rank owns a contiguous span of "
            f"{n_pages}//{world} logical pages")
    if num_blocks % world:
        raise ValueError(
            f"kv_shard={kv_shard!r} needs num_blocks ({num_blocks}) "
            f"divisible by the {label} ({world}) — the pool "
            f"splits into equal per-rank partitions")
    if num_blocks // world < 2:
        raise ValueError(
            f"kv_shard={kv_shard!r} needs num_blocks//world >= 2 "
            f"({num_blocks}//{world} = {num_blocks // world}): "
            f"every partition reserves its own null block and "
            f"still needs at least one allocatable page")
    if page_size % world:
        raise ValueError(
            f"kv_shard={kv_shard!r} needs page_size ({page_size}) "
            f"divisible by the {label} ({world}) — the sharded "
            f"chunked-prefill attend splits every scratch-extent rung "
            f"(a page multiple) into equal per-rank row spans")


def validate_mesh_geometry(*, mesh, tp_axis, kv_shard, cfg, max_seq,
                           num_blocks, page_size, spec_k=0,
                           sp_axis=None) -> int:
    """Reject impossible (mesh, engine-geometry) combinations with a
    loud ``ValueError`` at CONSTRUCTION — the alternative is a shape
    error deep inside a traced forward, long after the caller can tell
    which knob was wrong.  Returns the TOTAL mesh world the layout
    spans: the size along ``tp_axis`` for the 1-axis layouts, tp × sp
    for ``"heads+seq"`` (the 2D rejection matrix names which axis a
    failed divisibility belongs to).  ``spec_k`` rides along for
    API stability only — speculative decode serves every layout since
    the 4D-q SP combine landed (ISSUE 19)."""
    del spec_k  # spec × seq works now: the combine merges 4D partials
    if tp_axis not in mesh.axis_names:
        raise ValueError(
            f"tp_axis {tp_axis!r} is not an axis of the mesh "
            f"{mesh.axis_names}")
    if kv_shard not in KV_SHARDS:
        raise ValueError(
            f"kv_shard must be one of {KV_SHARDS}, got {kv_shard!r}")
    world = int(mesh.shape[tp_axis])
    if world < 1:
        raise ValueError(f"mesh axis {tp_axis!r} has size {world}")
    if kv_shard == "heads":
        _check_heads_geometry(cfg, world, kv_shard, "mesh world")
    elif kv_shard == "seq":
        _check_seq_geometry(max_seq, num_blocks, page_size, world,
                            kv_shard, "mesh world")
    else:  # heads+seq: the world must factor as tp x sp on NAMED axes
        if sp_axis is None:
            raise ValueError(
                "kv_shard='heads+seq' needs an sp_axis: the world must "
                "factor as tp x sp over two named mesh axes (weights/"
                "heads on tp, KV blocks on sp)")
        if sp_axis not in mesh.axis_names:
            raise ValueError(
                f"sp_axis {sp_axis!r} is not an axis of the mesh "
                f"{mesh.axis_names}")
        if sp_axis == tp_axis:
            raise ValueError(
                f"kv_shard='heads+seq' needs DISTINCT tp/sp axes, got "
                f"{tp_axis!r} for both — a 1-axis mesh cannot factor "
                f"the world as tp x sp")
        sp = int(mesh.shape[sp_axis])
        _check_heads_geometry(cfg, world, kv_shard,
                              f"tp axis {tp_axis!r}")
        _check_seq_geometry(max_seq, num_blocks, page_size, sp,
                            kv_shard, f"sp axis {sp_axis!r}")
        world = world * sp
    return world


@dataclasses.dataclass(frozen=True)
class _ShardCfg:
    """The per-shard config view the shared forwards see under TP:
    LOCAL head counts with the GLOBAL ``head_dim``/``dim`` — a plain
    ``dataclasses.replace(cfg, n_heads=...)`` would silently corrupt
    ``LlamaConfig.head_dim`` (a ``dim // n_heads`` property), so the
    fields the forwards read are pinned explicitly here."""

    n_heads: int
    n_kv_heads: int
    head_dim: int
    dim: int
    norm_eps: float
    rope_theta: float
    dtype: object
    attn_window: int
    attn_soft_cap: float


def _local_cfg(cfg, world: int):
    """The per-shard view of a TP-sharded model: local head counts (the
    shared forwards reshape QKV by ``cfg.n_heads``/``n_kv_heads``, and
    each rank's column shards hold exactly ``1/world`` of the heads).
    Everything else — dim, head_dim, norms, rope — stays global."""
    return _ShardCfg(n_heads=cfg.n_heads // world,
                     n_kv_heads=cfg.n_kv_heads // world,
                     head_dim=cfg.head_dim, dim=cfg.dim,
                     norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
                     dtype=cfg.dtype, attn_window=cfg.attn_window,
                     attn_soft_cap=cfg.attn_soft_cap)


# ---------------------------------------------------------------------------
# The two TP reduction seams (generate.py's ffn / out_proj hooks)
# ---------------------------------------------------------------------------


def _tp_out_proj(o2, layer, *, axis):
    """Row-parallel attention output projection: each rank contracts its
    local head columns against its ``wo`` row shard, ``psum`` completes
    the sum — ``generate._default_out_proj`` with the contraction split
    across ranks."""
    return jax.lax.psum(o2 @ layer["wo"], axis)


def _tp_ffn(h2, layer, *, axis):
    """Megatron MLP: column-parallel gate/up on the replicated
    activations, row-parallel down + ``psum`` — the same SwiGLU math as
    ``generate._dense_prompt_ffn`` over the local feature shard."""
    act = (jax.nn.silu((h2 @ layer["wgate"]).astype(jnp.float32))
           .astype(h2.dtype) * (h2 @ layer["wup"]))
    return jax.lax.psum(act @ layer["wdown"], axis)


# ---------------------------------------------------------------------------
# Per-shard forward bodies (call inside shard_map)
# ---------------------------------------------------------------------------


def tp_paged_decode_shard(params, pools, tables, kv_lens, token, active,
                          *, cfg, page, axis, world, impl, interpret,
                          ffn=None, out_proj=None):
    """Head-sharded twin of ``engine._paged_decode_forward``: QKV
    project onto the rank's head columns, the K/V scatter lands in the
    rank's pool shard, attention runs ``gqa_decode_paged_shard`` over
    the local heads (no combine — heads are independent), and the
    output/FFN row-parallel matmuls ``psum``.  ``tables``/``kv_lens``
    are replicated (the host-managed index is global); the returned
    logits are replicated, so sampling and commit stay bit-identical to
    the world-1 path.  The block-table addressing is the ENGINE's own
    forward — this only supplies the TP seams (local-head cfg + psum
    hooks), so the addressing can never diverge between world-1 and
    mesh.  ``ffn``/``out_proj`` override the default TP seams (the
    w8a8 serving hooks ride here — same psum count, quantized
    contraction)."""
    from triton_dist_tpu.serve.engine import _paged_decode_forward

    return _paged_decode_forward(
        params, pools, tables, kv_lens, token, active, cfg=cfg,
        page=page, impl=impl, interpret=interpret,
        fwd_cfg=_local_cfg(cfg, world),
        ffn=ffn or functools.partial(_tp_ffn, axis=axis),
        out_proj=out_proj or functools.partial(_tp_out_proj, axis=axis))


def tp_paged_verify_shard(params, pools, tables, kv_lens, chunk, active,
                          *, cfg, page, axis, world, impl, interpret,
                          ffn=None, out_proj=None):
    """Head-sharded twin of ``engine._paged_verify_forward`` — the
    multi-token verify under shard_map; like the decode twin, the
    engine's own forward with the TP seams supplied."""
    from triton_dist_tpu.serve.engine import _paged_verify_forward

    return _paged_verify_forward(
        params, pools, tables, kv_lens, chunk, active, cfg=cfg,
        page=page, impl=impl, interpret=interpret,
        fwd_cfg=_local_cfg(cfg, world),
        ffn=ffn or functools.partial(_tp_ffn, axis=axis),
        out_proj=out_proj or functools.partial(_tp_out_proj, axis=axis))


def _rebase_local(ids, *, axis, world, num_blocks):
    """THE global→local block-id rebase of the seq layout, shared by
    every per-shard body that touches the pools: rank ``r`` owns global
    blocks ``[r*nb_loc, (r+1)*nb_loc)``; returns ``(mine, local)``
    where foreign/padded ids (another rank's blocks, the global null)
    map to local row 0 — the rank's own reserved null, so a non-owner's
    write or copy degenerates to a null self-touch exactly like an
    inactive row's."""
    nb_loc = num_blocks // world
    lo = jax.lax.axis_index(axis) * nb_loc
    mine = (ids >= lo) & (ids < lo + nb_loc)
    return mine, jnp.where(mine, ids - lo, 0)


def sp_paged_decode_shard(params, pools, tables, kv_lens, token, active,
                          *, cfg, page, axis, world, num_blocks,
                          n_pages_max, impl, interpret, fwd_cfg=None,
                          ffn=None, out_proj=None):
    """Sequence-sharded twin of ``engine._paged_decode_forward``:
    weights replicated, pools sharded on the BLOCK axis — rank ``r``
    holds global blocks ``[r*nb_loc, (r+1)*nb_loc)``, which the
    partitioned :class:`serve.block_manager.BlockManager` dedicates to
    the logical pages of rank ``r``'s sequence span.  The block table
    is global; each rank slices its span and rebases the ids to local
    pool rows (foreign/padded entries — including another rank's
    blocks and the global null — map to local row 0, the rank's own
    reserved null).  Attention goes through
    ``sp_gqa_decode_paged_shard`` (local lengths + LSE combine), so
    the returned logits are replicated.  Quantized pools ride through
    unchanged: ``_scatter_kv`` and ``_pool_views`` are both
    dict-aware, and the per-page scales feed the combine's dequant.

    ``axis``/``world`` are the SP axis; ``fwd_cfg``/``ffn``/
    ``out_proj`` thread the heads-TP seams through for the 2D
    ``"heads+seq"`` layout (local-head cfg + psum hooks on the tp
    axis) — the pool's head axis then holds the rank's local KV heads
    and the block addressing is untouched, so ONE body serves both
    layouts."""
    from triton_dist_tpu.serve.engine import (
        _page_slots,
        _pool_views,
        _scatter_kv,
    )

    n_loc = n_pages_max // world
    inc = active.astype(kv_lens.dtype)

    # The next write's physical slot, rebased: only the owning rank
    # writes the real row; everyone else's write redirects to ITS null
    # (local row 0) exactly like an inactive row.
    pool_row_g, in_page = _page_slots(tables, kv_lens, active, page=page)
    mine, pool_row = _rebase_local(pool_row_g, axis=axis, world=world,
                                   num_blocks=num_blocks)
    mine = mine & active
    pool_row = jnp.where(mine, pool_row, 0)
    in_page = jnp.where(mine, in_page, 0)

    def write_kv(li, pool, k, v):
        return _scatter_kv(pool, k, v, pool_row, in_page)

    me = jax.lax.axis_index(axis)
    lt = jax.lax.dynamic_slice_in_dim(tables, me * n_loc, n_loc, axis=1)
    _, lt = _rebase_local(lt, axis=axis, world=world,
                          num_blocks=num_blocks)

    def attend(li, q, pool):
        kq, vq, ks, vs = _pool_views(pool)
        return sp_gqa_decode_paged_shard(
            q, kq, vq, lt, kv_lens + inc, axis=axis,
            impl=impl, interpret=interpret, soft_cap=cfg.attn_soft_cap,
            window=cfg.attn_window, k_scale=ks, v_scale=vs)

    return _token_forward(params, pools, token, kv_lens,
                          cfg=fwd_cfg or cfg, write_kv=write_kv,
                          attend=attend, ffn=ffn, out_proj=out_proj)


def sp_paged_verify_shard(params, pools, tables, kv_lens, chunk, active,
                          *, cfg, page, axis, world, num_blocks,
                          n_pages_max, impl, interpret, fwd_cfg=None,
                          ffn=None, out_proj=None):
    """Sequence-sharded twin of ``engine._paged_verify_forward`` — the
    multi-token verify over block-sharded pools (ISSUE 19 debt (a):
    this body exists because ``sp_gqa_decode_paged_shard`` now merges
    queries×heads 4D partials).  The [B, T] write addressing is the
    engine forward's own math with the seq rebase applied elementwise:
    each of a row's T scatter targets redirects to the rank's local
    null unless the rank owns that block, so a verify chunk spanning a
    page boundary (and therefore possibly TWO ranks' partitions)
    writes each row exactly once fleet-wide.  Attention reads back
    through the rank's rebased table slice with GLOBAL ``kv_lens + T``
    — per-token causality rides the combine's unclipped local ends,
    exactly the contiguous SP verify contract.  TP seams as in
    :func:`sp_paged_decode_shard` (the 2D layout)."""
    from triton_dist_tpu.serve.engine import _pool_views, _scatter_kv

    n_loc = n_pages_max // world
    T = chunk.shape[1]
    n_pages = tables.shape[1]
    pos = kv_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None]  # [B, T]
    logical = jnp.minimum(pos // page, n_pages - 1)
    pool_row_g = jnp.take_along_axis(tables, logical, axis=1)      # [B, T]
    in_page = pos % page
    mine, pool_row = _rebase_local(pool_row_g, axis=axis, world=world,
                                   num_blocks=num_blocks)
    mine = mine & active[:, None]
    pool_row = jnp.where(mine, pool_row, 0)
    in_page = jnp.where(mine, in_page, 0)

    def write_kv(li, pool, k, v):
        return _scatter_kv(pool, k, v, pool_row, in_page)

    me = jax.lax.axis_index(axis)
    lt = jax.lax.dynamic_slice_in_dim(tables, me * n_loc, n_loc, axis=1)
    _, lt = _rebase_local(lt, axis=axis, world=world,
                          num_blocks=num_blocks)

    def attend(li, q, pool):
        kq, vq, ks, vs = _pool_views(pool)
        return sp_gqa_decode_paged_shard(
            q, kq, vq, lt, kv_lens + T, axis=axis,
            impl=impl, interpret=interpret, soft_cap=cfg.attn_soft_cap,
            window=cfg.attn_window, k_scale=ks, v_scale=vs)

    return _multitoken_forward(params, pools, chunk, pos,
                               cfg=fwd_cfg or cfg, write_kv=write_kv,
                               attend=attend, ffn=ffn,
                               out_proj=out_proj)


def tp_paged_decode_horizon_shard(params, pools, tables, kv_lens, token,
                                  active, eos_done, limits, counts,
                                  base_keys, temps, top_ks, top_ps,
                                  greedy, eos_ids, *, H, all_greedy, cfg,
                                  page, axis, world, impl, interpret,
                                  ffn=None, out_proj=None):
    """The fused decode horizon under shard_map (heads): the engine's
    ``_paged_decode_horizon`` scan with the TP per-step forward swapped
    in — on-device sampling and every carry stay replicated, so the
    token bursts are bit-identical to the world-1 scan."""
    from triton_dist_tpu.serve.engine import _paged_decode_horizon

    fwd = functools.partial(tp_paged_decode_shard, cfg=cfg, page=page,
                            axis=axis, world=world, impl=impl,
                            interpret=interpret, ffn=ffn,
                            out_proj=out_proj)
    return _paged_decode_horizon(
        params, pools, tables, kv_lens, token, active, eos_done, limits,
        counts, base_keys, temps, top_ks, top_ps, greedy, eos_ids, H=H,
        all_greedy=all_greedy, cfg=cfg, page=page, impl=impl,
        interpret=interpret, decode_fwd=fwd)


def sp_paged_decode_horizon_shard(params, pools, tables, kv_lens, token,
                                  active, eos_done, limits, counts,
                                  base_keys, temps, top_ks, top_ps,
                                  greedy, eos_ids, *, H, all_greedy, cfg,
                                  page, axis, world, num_blocks,
                                  n_pages_max, impl, interpret,
                                  fwd_cfg=None, ffn=None, out_proj=None):
    """The fused decode horizon over sequence-sharded pools: the same
    scan with the SP per-step forward (local spans + LSE combine).
    TP seams thread through for the 2D layout."""
    from triton_dist_tpu.serve.engine import _paged_decode_horizon

    fwd = functools.partial(sp_paged_decode_shard, cfg=cfg, page=page,
                            axis=axis, world=world,
                            num_blocks=num_blocks,
                            n_pages_max=n_pages_max, impl=impl,
                            interpret=interpret, fwd_cfg=fwd_cfg,
                            ffn=ffn, out_proj=out_proj)
    return _paged_decode_horizon(
        params, pools, tables, kv_lens, token, active, eos_done, limits,
        counts, base_keys, temps, top_ks, top_ps, greedy, eos_ids, H=H,
        all_greedy=all_greedy, cfg=cfg, page=page, impl=impl,
        interpret=interpret, decode_fwd=fwd)


def tp_spec_round_shard(params, draft_params, pools, dcaches, tables,
                        kv_lens, active, done, last_logits, dlast_logits,
                        counts, limits, k_rows, base_keys, temps, top_ks,
                        top_ps, greedy, eos_ids, *, K, all_greedy, cfg,
                        dcfg, page, axis, world, impl, interpret,
                        dimpl, dinterpret):
    """The whole fused speculative round under shard_map (heads): the
    target's verify + decode legs run head-sharded TP, the draft steps
    REPLICATED per rank (its slot-indexed batch caches are host-managed
    whole-batch state — sharding them would put the accept chain's
    inputs behind a gather), and the seeded accept/sampling math runs on
    replicated logits — bit-identical emissions per rank."""
    from triton_dist_tpu.serve.engine import (
        _draft_decode_forward,
        _spec_round_fused,
    )

    decode_fwd = functools.partial(tp_paged_decode_shard, cfg=cfg,
                                   page=page, axis=axis, world=world,
                                   impl=impl, interpret=interpret)
    verify_fwd = functools.partial(tp_paged_verify_shard, cfg=cfg,
                                   page=page, axis=axis, world=world,
                                   impl=impl, interpret=interpret)
    draft_step = functools.partial(_draft_decode_forward, cfg=dcfg,
                                   impl=dimpl, interpret=dinterpret)
    return _spec_round_fused(
        params, draft_params, pools, dcaches, tables, kv_lens, active,
        done, last_logits, dlast_logits, counts, limits, k_rows,
        base_keys, temps, top_ks, top_ps, greedy, eos_ids, K=K,
        all_greedy=all_greedy, cfg=cfg, page=page, impl=impl,
        interpret=interpret, draft_step=draft_step,
        decode_fwd=decode_fwd, verify_fwd=verify_fwd)


def sp_spec_round_shard(params, draft_params, pools, dcaches, tables,
                        kv_lens, active, done, last_logits, dlast_logits,
                        counts, limits, k_rows, base_keys, temps, top_ks,
                        top_ps, greedy, eos_ids, *, K, all_greedy, cfg,
                        dcfg, page, axis, world, num_blocks, n_pages_max,
                        impl, interpret, dimpl, dinterpret, fwd_cfg=None,
                        ffn=None, out_proj=None):
    """The fused speculative round over sequence-sharded pools (ISSUE 19
    debt (a) unlocked this: the 4D-q SP combine lets the verify leg run
    under ``seq``).  Target decode/verify use the SP bodies — local
    pool spans + LSE combine — while the draft steps stay REPLICATED
    per rank for the same host-managed-cache reason as the heads
    layout; accept/sampling math runs on replicated logits.  TP seams
    (``fwd_cfg``/``ffn``/``out_proj``) thread into the target legs for
    ``heads+seq``; the draft is NEVER head-sharded (its cfg would need
    its own local view for marginal win)."""
    from triton_dist_tpu.serve.engine import (
        _draft_decode_forward,
        _spec_round_fused,
    )

    decode_fwd = functools.partial(sp_paged_decode_shard, cfg=cfg,
                                   page=page, axis=axis, world=world,
                                   num_blocks=num_blocks,
                                   n_pages_max=n_pages_max,
                                   impl=impl, interpret=interpret,
                                   fwd_cfg=fwd_cfg, ffn=ffn,
                                   out_proj=out_proj)
    verify_fwd = functools.partial(sp_paged_verify_shard, cfg=cfg,
                                   page=page, axis=axis, world=world,
                                   num_blocks=num_blocks,
                                   n_pages_max=n_pages_max,
                                   impl=impl, interpret=interpret,
                                   fwd_cfg=fwd_cfg, ffn=ffn,
                                   out_proj=out_proj)
    draft_step = functools.partial(_draft_decode_forward, cfg=dcfg,
                                   impl=dimpl, interpret=dinterpret)
    return _spec_round_fused(
        params, draft_params, pools, dcaches, tables, kv_lens, active,
        done, last_logits, dlast_logits, counts, limits, k_rows,
        base_keys, temps, top_ks, top_ps, greedy, eos_ids, K=K,
        all_greedy=all_greedy, cfg=cfg, page=page, impl=impl,
        interpret=interpret, draft_step=draft_step,
        decode_fwd=decode_fwd, verify_fwd=verify_fwd)


def tp_chunk_forward_shard(params, chunk, caches, prefix_len, n_valid, *,
                           cfg, extent, axis, world, impl, interpret,
                           quantized=False, ffn=None, out_proj=None):
    """Head-sharded chunked prefill: ``generate._chunk_forward`` with
    the local-head cfg and the TP reduction hooks — each rank computes
    its head columns of the chunk's K/V into its shard of the prefill
    scratch, attention runs per-head over the local scratch, and the
    out-proj/FFN seams ``psum``.  ``mesh``/``axis`` stay None inside:
    the per-rank scratch is head-local, never sequence-sharded.
    ``quantized`` writes the chunk's K/V into int8+scale scratch
    (the rank's local heads quantize independently — same per-(head,
    position) absmax math as world-1, so the pages are bit-identical)."""
    return _chunk_forward(
        params, chunk, caches, prefix_len, cfg=_local_cfg(cfg, world),
        quantized=quantized,
        ffn=ffn or functools.partial(_tp_ffn, axis=axis),
        out_proj=out_proj or functools.partial(_tp_out_proj, axis=axis),
        extent=extent, n_valid=n_valid, impl=impl, interpret=interpret)


def rep_chunk_forward_shard(params, chunk, caches, prefix_len, n_valid,
                            *, cfg, extent, impl, interpret,
                            quantized=False):
    """Replicated chunked prefill (the DRAFT model under any mesh):
    every rank runs the identical world-1 chunk forward.  The target
    model no longer rides this under ``kv_shard='seq'`` — ISSUE 19
    debt (b) moved it to :func:`sp_chunk_forward_shard`."""
    return _chunk_forward(params, chunk, caches, prefix_len, cfg=cfg,
                          quantized=quantized, extent=extent,
                          n_valid=n_valid, impl=impl, interpret=interpret)


def sp_chunk_forward_shard(params, chunk, caches, prefix_len, n_valid,
                           *, cfg, extent, axis, world, impl, interpret,
                           quantized=False, fwd_cfg=None, ffn=None,
                           out_proj=None):
    """Sequence-sharded chunked prefill (ISSUE 19 debt (b)): the chunk's
    QKV/FFN math and the scratch K/V WRITE stay replicated — the
    partitioned allocator's page→partition map does not align with an
    even row-split of an extent-``m`` scratch, so the scratch must hold
    the whole extent on every rank for the downstream page scatter —
    but the O(c·extent) attention read, the term that dominates long
    prompts, now shards: each rank slices its ``extent/world`` span out
    of the cache view (geometry guarantees ``page_size % world``, and
    every ladder rung is a page multiple, so the split is exact) and
    attends via ``sp_gqa_decode_shard``; the partials LSE-combine over
    ``axis``.  The causal rule rides the combine's unclipped local ends
    — chunk row ``i`` sees positions ``<= prefix + i`` exactly as the
    dense mask does, and padded K rows (``n_valid``) stay hidden the
    same way they do in world-1.  TP seams (``fwd_cfg``/``ffn``/
    ``out_proj``) thread through for ``heads+seq``, where the scratch's
    head axis is already the rank's local shard."""
    me = jax.lax.axis_index(axis)

    def attend(q, k_view, v_view, plen, *, k_scale=None, v_scale=None):
        s_loc = k_view.shape[2] // world

        def loc(x):
            return (None if x is None else
                    jax.lax.dynamic_slice_in_dim(x, me * s_loc, s_loc,
                                                 axis=2))

        B, c = q.shape[0], q.shape[1]
        lens = jnp.full((B,), c, jnp.int32) + plen
        return sp_gqa_decode_shard(
            q, loc(k_view), loc(v_view), lens, axis=axis, impl="auto",
            interpret=interpret, k_scale=loc(k_scale),
            v_scale=loc(v_scale), soft_cap=cfg.attn_soft_cap,
            window=cfg.attn_window).astype(jnp.float32)

    return _chunk_forward(params, chunk, caches, prefix_len,
                          cfg=fwd_cfg or cfg, quantized=quantized,
                          ffn=ffn, out_proj=out_proj, extent=extent,
                          n_valid=n_valid, impl=impl, interpret=interpret,
                          attend=attend)


# -- page scatter / gather / COW over sharded pools -------------------------


def sp_fill_pool_pages_shard(pools, scratch, ids, *, page, axis, world,
                             num_blocks):
    """Sequence-sharded page scatter: ``ids`` are GLOBAL block ids per
    scratch page; each rank rebases its own ids to local pool rows and
    scatters only those pages — foreign and padded entries land in the
    rank's local null (row 0), exactly where world-1 scatters its
    padding."""
    from triton_dist_tpu.serve.engine import _fill_pool_pages

    _, loc = _rebase_local(ids, axis=axis, world=world,
                           num_blocks=num_blocks)
    return _fill_pool_pages(pools, scratch, loc, page=page)


def sp_gather_pool_pages_shard(pools, ids, *, page, axis, world,
                               num_blocks):
    """Sequence-sharded page gather (the warm-prefix / drain read-back):
    each rank gathers its own pages into the replicated scratch layout,
    zeroes the rows it does not own, and a ``psum`` assembles the full
    scratch — every row has exactly one owner, so the sum is exact
    (adding zeros never perturbs floats)."""
    from triton_dist_tpu.serve.engine import _gather_pool_pages

    mine, loc = _rebase_local(ids, axis=axis, world=world,
                              num_blocks=num_blocks)
    sc = _gather_pool_pages(pools, loc, page=page)
    rows = jnp.repeat(mine, page)

    def _own(x):
        # scratch row axis is 2 for both layouts: [1,H,S,D] pages and
        # [1,H,S] per-page scales — broadcast the ownership mask over
        # whatever trails it (int8 pages psum exactly: one owner per
        # row, everyone else contributes true zeros)
        r = rows.reshape((1, 1, -1) + (1,) * (x.ndim - 3))
        return jnp.where(r, x, jnp.zeros((), x.dtype))

    sc = jax.tree_util.tree_map(_own, sc)
    return jax.lax.psum(sc, axis)


def sp_copy_pool_block_shard(pools, src, dst, *, axis, world, num_blocks):
    """Sequence-sharded COW page copy: the partitioned allocator keeps
    both halves of a split in one partition, so exactly the owning rank
    copies (everyone else degenerates to a null→null self-copy)."""
    from triton_dist_tpu.serve.engine import _copy_pool_block

    _, s = _rebase_local(src, axis=axis, world=world,
                         num_blocks=num_blocks)
    # the allocator keeps both halves of a split in one partition, so
    # dst rebases under the same ownership (foreign ranks get 0 -> 0)
    _, d = _rebase_local(dst, axis=axis, world=world,
                         num_blocks=num_blocks)
    return _copy_pool_block(pools, s, d)


# ---------------------------------------------------------------------------
# ShardedProgram — jit(shard_map) + canonical argument placement
# ---------------------------------------------------------------------------


def _place(x, sharding):
    """Commit ``x`` onto ``sharding`` unless it already carries it —
    the one-signature-per-program guarantee (module docstring).
    Tracers pass through: under a re-trace (the jaxpr auditor replaying
    a captured signature) placement is a runtime concern and a tracer
    carries no sharding to inspect."""
    if isinstance(x, jax.core.Tracer):
        return x
    if isinstance(x, jax.Array) and x.sharding == sharding:
        return x
    return jax.device_put(x, sharding)


def _shardings_of(mesh, spec_tree):
    """PartitionSpec tree → NamedSharding tree (specs are pytrees of
    tuples, so they must be treated as leaves)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


class ShardedProgram:
    """One engine device program on a mesh: ``jax.jit(jax.shard_map(
    body))`` with per-argument canonical placement and a bounded
    static-kwargs ladder.

    - Positional args are pytrees matched leaf-wise against
      ``in_specs``; every leaf is ``device_put`` to its declared
      ``NamedSharding`` unless already there — host-built and
      device-carried calls hit the SAME executable (the PR-7 cache-fork
      fix; module docstring).
    - Keyword args are STATIC trace parameters (the horizon's ``H``,
      the spec round's ``K``, ...): each distinct combination memoizes
      one jitted closure, exactly like ``static_argnames`` — and
      ``_cache_size()`` sums the inner caches so ``CountingJit``'s
      hit/miss accounting (and warmup's fixed-point test) keep working
      unchanged.
    - ``donate_argnums`` applies to the placed arrays; the engine
      already reassigns donated carries from the outputs.
    - ``timer`` (optional ``(label, ms)`` callable, the
      ``jit_cache.CountingJit`` protocol): every call's wall time —
      placement included, it is part of what the program costs — is
      reported under ``name`` suffixed with the ``timed_statics``
      kwargs' values (``decode_horizon[H=8]``).  The engine wires its
      CountingJit wrapper's timer instead (one seam for mesh and
      world-1 programs); this hook serves direct ShardedProgram users.
    """

    def __init__(self, body, mesh, in_specs, out_specs, *,
                 donate_argnums=(), name=None, timer=None,
                 timed_statics=()):
        self.body = body
        self.mesh = mesh
        self.in_specs = tuple(in_specs)
        self.out_specs = out_specs
        self.donate_argnums = tuple(donate_argnums)
        self.name = name or getattr(body, "__name__", "sharded_program")
        self.timer = timer
        self.timed_statics = tuple(timed_statics)
        self._placements = tuple(_shardings_of(mesh, s)
                                 for s in self.in_specs)
        self._jits: dict = {}
        #: statics-key -> abstracted args of the first call per rung
        #: (the jaxpr auditor's re-trace seed, like CountingJit's)
        self.captured: dict = {}

    def _prog(self, statics: tuple):
        prog = self._jits.get(statics)
        if prog is None:
            fn = (functools.partial(self.body, **dict(statics))
                  if statics else self.body)
            prog = jax.jit(
                jax.shard_map(fn, mesh=self.mesh, in_specs=self.in_specs,
                              out_specs=self.out_specs, check_vma=False),
                donate_argnums=self.donate_argnums)
            self._jits[statics] = prog
        return prog

    def place(self, i: int, value):
        """Canonical placement of argument ``i`` (exposed so the engine
        can pre-place long-lived carries like the pools at init)."""
        return jax.tree_util.tree_map(_place, value, self._placements[i])

    def __call__(self, *args, **statics):
        timer = self.timer
        before = self._cache_size() if timer is not None else 0
        t0 = time.perf_counter() if timer is not None else 0.0
        placed = tuple(
            jax.tree_util.tree_map(_place, a, p)
            for a, p in zip(args, self._placements))
        key = tuple(sorted(statics.items()))
        if key not in self.captured and \
                len(self.captured) < jit_cache.MAX_CAPTURED_SIGNATURES:
            self.captured[key] = jit_cache.abstract_signature(
                placed, dict(statics))
        out = self._prog(key)(*placed)
        # compile calls (cache grew) stay out of the distributions —
        # the same rule as CountingJit: stalls are compile accounting,
        # not program wall time
        if timer is not None and self._cache_size() == before:
            label = self.name
            for k in self.timed_statics:
                v = statics.get(k)
                if v is not None:
                    label = f"{label}[{k}={v}]"
            timer(label, (time.perf_counter() - t0) * 1e3)
        return out

    def _cache_size(self) -> int:
        # CountingJit keys its miss accounting on this (a fresh static
        # rung AND a fresh signature within a rung both count — the
        # same events a plain jit's cache growth reports).
        return sum(p._cache_size() for p in self._jits.values())


class MeshChunkJit:
    """The mesh chunk-prefill program behind ``Generator._chunk_jit``'s
    call convention (``(params, buf, scratch, prefix, *, quantized,
    extent, n_valid)`` with ``quantized``/``extent`` static and
    ``n_valid`` traced): one :class:`ShardedProgram` per extent rung,
    ``n_valid`` folded into the positional args.  ``quantized`` is a
    CONSTRUCTION property here, not a per-call rung: the pool dtype is
    engine geometry, the chunk bodies are built for exactly one dtype,
    and a call asking for the other is a wiring bug worth an assert."""

    def __init__(self, maker, *, quantized=False):
        self._maker = maker     # extent -> ShardedProgram
        self._progs: dict = {}
        self._quantized = bool(quantized)

    def __call__(self, params, buf, scratch, prefix, *, quantized,
                 extent, n_valid):
        assert quantized == self._quantized, (
            "mesh chunk prefill was built for "
            f"quantized={self._quantized}; called with {quantized}")
        prog = self._progs.get(extent)
        if prog is None:
            prog = self._maker(extent)
            self._progs[extent] = prog
        return prog(params, buf, scratch, prefix, n_valid)

    def _cache_size(self) -> int:
        return sum(p._cache_size() for p in self._progs.values())


# ---------------------------------------------------------------------------
# Program construction (the engine's mesh-mode __init__ calls this)
# ---------------------------------------------------------------------------


def collective_seams(cfg, *, kv_shard: str, draft_cfg=None) -> dict:
    """Declared collective seams per engine program — the contract the
    jaxpr auditor (``analysis/jaxpr_audit.py``) enforces: any
    collective primitive a program traces that is NOT declared here is
    a violation, and declared counts must match exactly.

    ``kv_shard="heads"`` (Megatron TP): the ONLY collectives in any
    forward are the two row-parallel ``psum``s per layer (attn
    out-proj, ``_tp_out_proj``; FFN down, ``_tp_ffn``) — 2 x n_layers
    per forward, nothing in per-rank attention, sampling, or the page
    programs.  ``kv_shard="seq"`` (SP flash-decode): one inter-rank
    LSE-combine gather per layer in EVERY forward — decode, verify,
    horizon AND chunked prefill, whose attention read shards since
    ISSUE 19 debt (b) (``sp_chunk_forward_shard``) — and one ``psum``
    in the page gather (``sp_gather_pool_pages_shard`` zeroes unowned
    rows and psum-assembles the full gather).  Spec rounds chain draft
    (replicated — collective-free) and target forwards: K+1 target
    forwards for the K-step draft scan + verify + closing decode... the
    spec round's exact chain is 2 target forwards traced (verify +
    closing decode, the draft scan is replicated), so 2x the
    per-forward seam count.  ``kv_shard="heads+seq"`` composes: every
    target forward carries BOTH the 2 TP psums and the 1 SP gather per
    layer (the axes never mix — psum on tp, all_gather on sp; the
    schedule-level story is the ``hier_sp_combine`` two-phase proof in
    analysis/comm_schedule.py), and the page programs keep the seq
    layout's counts (the head axis moves no bytes between ranks).
    """
    n = cfg.n_layers
    if kv_shard == "heads":
        fwd = {"psum": 2 * n}
        seams = {
            "paged_decode": dict(fwd),
            "paged_verify": dict(fwd),
            "decode_horizon": dict(fwd),
            "prefill_chunk": dict(fwd),
            # page scatter/gather/COW move KV bytes inside each rank's
            # own head shard: collective-free.
            "fill_pages": {}, "load_pages": {}, "cow_copy": {},
            # spec round: draft scan replicated (collective-free),
            # verify + closing decode are 2 target forwards.
            "spec_round": {"psum": 2 * (2 * n)},
            "draft_tail_step": {},
            "draft_prefill": {}, "draft_join": {}, "draft_step": {},
            "draft_fill_pages": {}, "draft_load_pages": {},
        }
        return seams
    if kv_shard in ("seq", "heads+seq"):
        fwd = {"all_gather": n}
        if kv_shard == "heads+seq":
            fwd["psum"] = 2 * n
        spec = {k: 2 * v for k, v in fwd.items()}
        return {
            "paged_decode": dict(fwd),
            "paged_verify": dict(fwd),
            "decode_horizon": dict(fwd),
            # chunked prefill shards its attention read (debt (b)):
            # same per-layer combine gather as the decode forwards.
            "prefill_chunk": dict(fwd),
            "fill_pages": {},
            "load_pages": {"psum": 1},
            "cow_copy": {},
            "spec_round": spec,
            "draft_tail_step": {},
            "draft_prefill": {}, "draft_join": {}, "draft_step": {},
            "draft_fill_pages": {}, "draft_load_pages": {},
        }
    raise ValueError(f"unknown kv_shard {kv_shard!r}")


def replicated_like(tree):
    """All-``P()`` spec tree matching ``tree``'s structure."""
    return jax.tree_util.tree_map(lambda _: P(), tree)


def build_programs(*, mesh, tp_axis, kv_shard, cfg, params, page_size,
                   num_blocks, n_pages_max, impl, interpret,
                   horizon: int, draft=None, draft_params=None,
                   spec_fused: bool = False,
                   prefix_cache: bool = False,
                   kv_quant: bool = False,
                   w8a8: bool = False,
                   sp_axis=None) -> dict:
    """All mesh device programs for one engine, keyed by the engine's
    program names (``paged_decode``, ``paged_verify``, ``fill_pages``,
    ``load_pages``, ``cow_copy``, ``decode_horizon``, ``prefill_chunk``
    — plus the draft family on spec engines).  Shapes/donation mirror
    the world-1 programs exactly, so warmup, metrics, and the step loop
    need no mesh-specific branches past construction.

    ``kv_quant`` swaps every pool/scratch spec for the dict-structured
    ``{"q": spec, "s": spec}`` twin — the SAME PartitionSpec legally
    covers both planes (heads shards axis 1 = Hkv of the 4D pages and
    the 3D scales alike; seq shards the shared block axis 0), and the
    forward/page bodies are already dict-aware, so the program set and
    its collective seams are unchanged.  ``w8a8`` (heads only — the
    engine rejects it elsewhere) swaps ``param_specs`` for
    ``w8a8_serve_param_specs`` and the TP reduction seams for the
    quantized serving hooks: same one-psum-per-seam shape, int8
    contraction inside.

    ``kv_shard="heads+seq"`` composes the two layouts on a 2D mesh:
    params/scratch shard their head axes on ``tp_axis`` exactly as the
    heads layout, pools shard ``P(sp_axis, tp_axis)`` — block axis over
    sp, head axis over tp — and every body is the SP body with the TP
    seams (local-head cfg + psum hooks) threaded through.  The
    BlockManager partition count is the SP world (``out["sp_world"]``),
    not the total world."""
    axis = tp_axis
    heads = kv_shard == "heads"
    two_d = kv_shard == "heads+seq"
    if two_d:
        tp_world = int(mesh.shape[tp_axis])
        sp_world = int(mesh.shape[sp_axis])
        world = tp_world * sp_world
        sp = sp_axis
    else:
        world = int(mesh.shape[axis])
        tp_world = world if heads else 1
        sp_world = 1 if heads else world
        sp = axis
    if heads:
        pool_spec = P(None, axis)
    elif two_d:
        pool_spec = P(sp_axis, tp_axis)
    else:
        pool_spec = P(axis)
    kv_spec = ({"q": pool_spec, "s": pool_spec} if kv_quant
               else pool_spec)
    pools_specs = [(kv_spec, kv_spec)] * cfg.n_layers
    sp_hooks = {}
    if heads:
        if w8a8:
            from triton_dist_tpu.models.llama_w8a8 import (
                w8a8_serve_ffn,
                w8a8_serve_out_proj,
                w8a8_serve_param_specs,
            )

            p_specs = w8a8_serve_param_specs(cfg, axis)
            hooks = {
                "ffn": functools.partial(
                    w8a8_serve_ffn, axis=axis, impl=impl,
                    interpret=interpret),
                "out_proj": functools.partial(
                    w8a8_serve_out_proj, axis=axis, impl=impl,
                    interpret=interpret),
            }
        else:
            p_specs = param_specs(cfg, axis)
            hooks = {}
    elif two_d:
        p_specs = param_specs(cfg, tp_axis)
        sp_hooks = {
            "fwd_cfg": _local_cfg(cfg, tp_world),
            "ffn": functools.partial(_tp_ffn, axis=tp_axis),
            "out_proj": functools.partial(_tp_out_proj, axis=tp_axis),
        }
    else:
        p_specs = replicated_like(params)
    scratch_spec = P(None, tp_axis) if (heads or two_d) else P()
    sc_spec = ({"q": scratch_spec, "s": scratch_spec} if kv_quant
               else scratch_spec)

    out = {"pool_spec": pool_spec, "params_specs": p_specs,
           "world": world, "tp_world": tp_world, "sp_world": sp_world}

    if heads:
        decode_body = functools.partial(
            tp_paged_decode_shard, cfg=cfg, page=page_size, axis=axis,
            world=world, impl=impl, interpret=interpret, **hooks)
        verify_body = functools.partial(
            tp_paged_verify_shard, cfg=cfg, page=page_size, axis=axis,
            world=world, impl=impl, interpret=interpret, **hooks)
        horizon_body = functools.partial(
            tp_paged_decode_horizon_shard, cfg=cfg, page=page_size,
            axis=axis, world=world, impl=impl, interpret=interpret,
            **hooks)
        fill_body = functools.partial(
            __import_engine()._fill_pool_pages, page=page_size)
        load_body = functools.partial(
            __import_engine()._gather_pool_pages, page=page_size)
        cow_body = __import_engine()._copy_pool_block
        chunk_body = functools.partial(
            tp_chunk_forward_shard, cfg=cfg, axis=axis, world=world,
            impl=impl, interpret=interpret, quantized=kv_quant, **hooks)
    else:
        decode_body = functools.partial(
            sp_paged_decode_shard, cfg=cfg, page=page_size, axis=sp,
            world=sp_world, num_blocks=num_blocks,
            n_pages_max=n_pages_max, impl=impl, interpret=interpret,
            **sp_hooks)
        verify_body = functools.partial(
            sp_paged_verify_shard, cfg=cfg, page=page_size, axis=sp,
            world=sp_world, num_blocks=num_blocks,
            n_pages_max=n_pages_max, impl=impl, interpret=interpret,
            **sp_hooks)
        horizon_body = functools.partial(
            sp_paged_decode_horizon_shard, cfg=cfg, page=page_size,
            axis=sp, world=sp_world, num_blocks=num_blocks,
            n_pages_max=n_pages_max, impl=impl, interpret=interpret,
            **sp_hooks)
        fill_body = functools.partial(
            sp_fill_pool_pages_shard, page=page_size, axis=sp,
            world=sp_world, num_blocks=num_blocks)
        load_body = functools.partial(
            sp_gather_pool_pages_shard, page=page_size, axis=sp,
            world=sp_world, num_blocks=num_blocks)
        cow_body = functools.partial(
            sp_copy_pool_block_shard, axis=sp, world=sp_world,
            num_blocks=num_blocks)
        chunk_body = functools.partial(
            sp_chunk_forward_shard, cfg=cfg, axis=sp, world=sp_world,
            impl=impl, interpret=interpret, quantized=kv_quant,
            **sp_hooks)

    # (params, pools, tables, kv_lens, token/chunk, active)
    fwd_in = (p_specs, pools_specs, P(), P(), P(), P())
    out["paged_decode"] = ShardedProgram(
        decode_body, mesh, fwd_in, (pools_specs, P()),
        donate_argnums=(1,))
    out["paged_verify"] = ShardedProgram(
        verify_body, mesh, fwd_in, (pools_specs, P()),
        donate_argnums=(1,))
    if horizon > 1:
        out["decode_horizon"] = ShardedProgram(
            horizon_body, mesh,
            (p_specs, pools_specs) + (P(),) * 13,
            (pools_specs,) + (P(),) * 6, donate_argnums=(1,))
    out["fill_pages"] = ShardedProgram(
        fill_body, mesh,
        (pools_specs, [(sc_spec, sc_spec)] * cfg.n_layers, P()),
        pools_specs, donate_argnums=(0,))
    out["load_pages"] = ShardedProgram(
        load_body, mesh, (pools_specs, P()),
        [(sc_spec, sc_spec)] * cfg.n_layers)
    out["cow_copy"] = ShardedProgram(
        cow_body, mesh, (pools_specs, P(), P()), pools_specs,
        donate_argnums=(0,))

    def make_chunk(extent: int) -> ShardedProgram:
        return ShardedProgram(
            functools.partial(chunk_body, extent=extent), mesh,
            (p_specs, P(),
             [(sc_spec, sc_spec)] * cfg.n_layers, P(), P()),
            ([(sc_spec, sc_spec)] * cfg.n_layers, P()),
            donate_argnums=(2,))

    out["prefill_chunk"] = MeshChunkJit(make_chunk, quantized=kv_quant)

    if draft is not None and spec_fused:
        dcfg = draft.cfg
        d_specs = replicated_like(draft_params)
        dpools_specs = [(P(), P())] * dcfg.n_layers
        if heads:
            spec_body = functools.partial(
                tp_spec_round_shard, cfg=cfg, dcfg=dcfg, page=page_size,
                axis=axis, world=world, impl=impl, interpret=interpret,
                dimpl=draft.attn.ctx.impl,
                dinterpret=draft.attn.ctx.interpret)
        else:
            spec_body = functools.partial(
                sp_spec_round_shard, cfg=cfg, dcfg=dcfg, page=page_size,
                axis=sp, world=sp_world, num_blocks=num_blocks,
                n_pages_max=n_pages_max, impl=impl, interpret=interpret,
                dimpl=draft.attn.ctx.impl,
                dinterpret=draft.attn.ctx.interpret, **sp_hooks)
        out["spec_round"] = ShardedProgram(
            spec_body, mesh,
            (p_specs, d_specs, pools_specs, dpools_specs)
            + (P(),) * 15,
            (pools_specs, dpools_specs) + (P(),) * 9,
            donate_argnums=(2, 3))
        tail_body = functools.partial(
            __import_engine()._draft_decode_forward, cfg=dcfg,
            impl=draft.attn.ctx.impl, interpret=draft.attn.ctx.interpret)
        out["draft_tail_step"] = ShardedProgram(
            tail_body, mesh, (d_specs, dpools_specs, P(), P(), P()),
            (dpools_specs, P(), P()), donate_argnums=(1,))
        out["draft_join"] = ShardedProgram(
            __import_engine()._splice_draft_rows, mesh,
            (dpools_specs, P(), P(),
             [(P(), P())] * dcfg.n_layers, P(), P(), P()),
            (dpools_specs, P(), P()), donate_argnums=(0, 1, 2))
        dchunk_body = functools.partial(
            rep_chunk_forward_shard, cfg=dcfg,
            impl=draft.attn.ctx.impl, interpret=draft.attn.ctx.interpret)

        def make_draft_chunk(extent: int) -> ShardedProgram:
            return ShardedProgram(
                functools.partial(dchunk_body, extent=extent), mesh,
                (d_specs, P(), [(P(), P())] * dcfg.n_layers, P(), P()),
                ([(P(), P())] * dcfg.n_layers, P()), donate_argnums=(2,))

        out["draft_prefill"] = MeshChunkJit(make_draft_chunk)
        if prefix_cache:
            out["draft_fill_pages"] = ShardedProgram(
                functools.partial(__import_engine()._fill_pool_pages,
                                  page=page_size), mesh,
                (dpools_specs, [(P(), P())] * dcfg.n_layers, P()),
                dpools_specs, donate_argnums=(0,))
            out["draft_load_pages"] = ShardedProgram(
                functools.partial(__import_engine()._gather_pool_pages,
                                  page=page_size), mesh,
                (dpools_specs, P()), [(P(), P())] * dcfg.n_layers)
    return out


def __import_engine():
    """Deferred engine import: engine.py imports this module inside its
    constructor, so a module-level back-import would be circular."""
    from triton_dist_tpu.serve import engine

    return engine
