"""Mesh placement for :class:`serve.engine.ServeEngine` — TP weights +
sharded paged KV under ``shard_map`` (docs/serving.md "Sharded serving").

The engine's device programs (paged decode, multi-token verify, the
fused decode horizon, chunked prefill, the page scatter/gather/COW
trio, the fused speculative round) are all parameterized over cache
addressing and the two weight-reduction seams (``generate._token_forward``
/ ``_multitoken_forward`` / ``_chunk_forward``'s ``write_kv`` /
``attend`` / ``ffn`` / ``out_proj`` hooks) — this module instantiates
them PER-SHARD and wraps each in ``jax.jit(jax.shard_map(...))`` so the
same engine step loop, scheduler, and block tables drive a multi-chip
forward.  Two KV layouts:

- ``kv_shard="heads"`` — Megatron-style tensor parallelism: weights
  shard by ``models.llama.param_specs`` (QKV/up-gate column-parallel,
  attn-out/down row-parallel + ``psum``), the paged pools shard on the
  KV-head axis, and each rank runs ``gqa_decode_paged_shard`` over its
  own heads (attention is head-independent, so no inter-rank combine
  exists on the attention path).  Supports everything the world-1
  engine does, speculative rounds included (the draft model runs
  replicated per rank — its batch caches are slot-indexed host-managed
  state that must stay whole on every rank).
- ``kv_shard="seq"`` — SP flash-decode (the reference's headline 1→32
  scaling, SURVEY.md §5): pools shard on the BLOCK axis, each rank
  holds the pages of its contiguous sequence span, attention goes
  through ``sp_gqa_decode_paged_shard`` (per-rank local lengths + the
  LSE combine) with the rank's slice of the block table rebased to
  local pool rows.  Weights stay replicated (the decode-serving layout
  of models/generate.py: the sharded thing is the KV cache).
  Speculative engines are REJECTED at construction — the paged SP
  combine only merges single-token partials (the loud assert
  tests/test_serve_engine.py pins), and a verify chunk is multi-token
  by definition.

**The executable-cache fork (the PR-7 problem, solved here).**  A
mesh-placed program's outputs carry ``NamedSharding`` while host-built
arrays carry single-device placements, and jax's jit cache keys on the
argument shardings — so one traced program would split into host-built
vs device-carried executable flavors that ``warmup()`` cannot
enumerate (the compile-miss counter would tick under traffic).
:class:`ShardedProgram` therefore CANONICALIZES every argument at the
call seam: each arg is ``device_put`` onto its declared
``NamedSharding`` unless it already carries it, so every call of a
program presents ONE signature and the cache holds exactly one
executable per (shapes, statics) — ``warmup()`` reaches the same
compile fixed point as world-1 and the miss counter stays flat.

Bit-exactness note: per-head attention, column-parallel projections and
the replicated sampling/commit path are arithmetically identical to
world-1; the row-parallel ``psum`` seams reduce in shard-major order,
which the oracle tests pin stream-exact on the test models (the same
standard tests/test_generate.py holds the SP combine to at world 4).
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.kernels.flash_decode import sp_gqa_decode_paged_shard
from triton_dist_tpu.models.generate import _chunk_forward, _token_forward
from triton_dist_tpu.models.llama import param_specs
from triton_dist_tpu.runtime import jit_cache


# ---------------------------------------------------------------------------
# Geometry validation — the loud construction-time rejection matrix
# ---------------------------------------------------------------------------


KV_SHARDS = ("heads", "seq")


def validate_mesh_geometry(*, mesh, tp_axis, kv_shard, cfg, max_seq,
                           num_blocks, page_size, spec_k=0) -> int:
    """Reject impossible (mesh, engine-geometry) combinations with a
    loud ``ValueError`` at CONSTRUCTION — the alternative is a shape
    error deep inside a traced forward, long after the caller can tell
    which knob was wrong.  Returns the mesh world size along
    ``tp_axis``."""
    if tp_axis not in mesh.axis_names:
        raise ValueError(
            f"tp_axis {tp_axis!r} is not an axis of the mesh "
            f"{mesh.axis_names}; ServeEngine shards over exactly one "
            f"named mesh axis")
    if kv_shard not in KV_SHARDS:
        raise ValueError(
            f"kv_shard must be one of {KV_SHARDS}, got {kv_shard!r}")
    world = int(mesh.shape[tp_axis])
    if world < 1:
        raise ValueError(f"mesh axis {tp_axis!r} has size {world}")
    if kv_shard == "heads":
        if cfg.n_kv_heads % world:
            raise ValueError(
                f"kv_shard='heads' needs n_kv_heads ({cfg.n_kv_heads}) "
                f"divisible by the mesh world ({world}) — each rank "
                f"must own whole KV heads of the paged pools")
        if cfg.n_heads % world:
            raise ValueError(
                f"kv_shard='heads' needs n_heads ({cfg.n_heads}) "
                f"divisible by the mesh world ({world}) — the "
                f"column-parallel QKV split assigns whole query heads "
                f"per rank")
        if cfg.ffn_dim % world:
            raise ValueError(
                f"TP weights need ffn_dim ({cfg.ffn_dim}) divisible by "
                f"the mesh world ({world}) — wgate/wup shard by "
                f"columns, wdown by rows")
    else:  # seq
        if spec_k:
            raise ValueError(
                "kv_shard='seq' cannot serve speculative engines: the "
                "paged SP decode combine merges SINGLE-token partials "
                "only (sp_gqa_decode_paged_shard's 3D-q contract), and "
                "a verify chunk is multi-token by definition — use "
                "kv_shard='heads' for spec serving on a mesh")
        n_pages = max_seq // page_size
        if n_pages % world:
            raise ValueError(
                f"kv_shard='seq' needs max_seq/page_size ({n_pages} "
                f"logical pages) divisible by the mesh world ({world}) "
                f"— each rank owns a contiguous span of "
                f"{n_pages}//{world} logical pages")
        if num_blocks % world:
            raise ValueError(
                f"kv_shard='seq' needs num_blocks ({num_blocks}) "
                f"divisible by the mesh world ({world}) — the pool "
                f"splits into equal per-rank partitions")
        if num_blocks // world < 2:
            raise ValueError(
                f"kv_shard='seq' needs num_blocks//world >= 2 "
                f"({num_blocks}//{world} = {num_blocks // world}): "
                f"every partition reserves its own null block and "
                f"still needs at least one allocatable page")
    return world


@dataclasses.dataclass(frozen=True)
class _ShardCfg:
    """The per-shard config view the shared forwards see under TP:
    LOCAL head counts with the GLOBAL ``head_dim``/``dim`` — a plain
    ``dataclasses.replace(cfg, n_heads=...)`` would silently corrupt
    ``LlamaConfig.head_dim`` (a ``dim // n_heads`` property), so the
    fields the forwards read are pinned explicitly here."""

    n_heads: int
    n_kv_heads: int
    head_dim: int
    dim: int
    norm_eps: float
    rope_theta: float
    dtype: object
    attn_window: int
    attn_soft_cap: float


def _local_cfg(cfg, world: int):
    """The per-shard view of a TP-sharded model: local head counts (the
    shared forwards reshape QKV by ``cfg.n_heads``/``n_kv_heads``, and
    each rank's column shards hold exactly ``1/world`` of the heads).
    Everything else — dim, head_dim, norms, rope — stays global."""
    return _ShardCfg(n_heads=cfg.n_heads // world,
                     n_kv_heads=cfg.n_kv_heads // world,
                     head_dim=cfg.head_dim, dim=cfg.dim,
                     norm_eps=cfg.norm_eps, rope_theta=cfg.rope_theta,
                     dtype=cfg.dtype, attn_window=cfg.attn_window,
                     attn_soft_cap=cfg.attn_soft_cap)


# ---------------------------------------------------------------------------
# The two TP reduction seams (generate.py's ffn / out_proj hooks)
# ---------------------------------------------------------------------------


def _tp_out_proj(o2, layer, *, axis):
    """Row-parallel attention output projection: each rank contracts its
    local head columns against its ``wo`` row shard, ``psum`` completes
    the sum — ``generate._default_out_proj`` with the contraction split
    across ranks."""
    return jax.lax.psum(o2 @ layer["wo"], axis)


def _tp_ffn(h2, layer, *, axis):
    """Megatron MLP: column-parallel gate/up on the replicated
    activations, row-parallel down + ``psum`` — the same SwiGLU math as
    ``generate._dense_prompt_ffn`` over the local feature shard."""
    act = (jax.nn.silu((h2 @ layer["wgate"]).astype(jnp.float32))
           .astype(h2.dtype) * (h2 @ layer["wup"]))
    return jax.lax.psum(act @ layer["wdown"], axis)


# ---------------------------------------------------------------------------
# Per-shard forward bodies (call inside shard_map)
# ---------------------------------------------------------------------------


def tp_paged_decode_shard(params, pools, tables, kv_lens, token, active,
                          *, cfg, page, axis, world, impl, interpret,
                          ffn=None, out_proj=None):
    """Head-sharded twin of ``engine._paged_decode_forward``: QKV
    project onto the rank's head columns, the K/V scatter lands in the
    rank's pool shard, attention runs ``gqa_decode_paged_shard`` over
    the local heads (no combine — heads are independent), and the
    output/FFN row-parallel matmuls ``psum``.  ``tables``/``kv_lens``
    are replicated (the host-managed index is global); the returned
    logits are replicated, so sampling and commit stay bit-identical to
    the world-1 path.  The block-table addressing is the ENGINE's own
    forward — this only supplies the TP seams (local-head cfg + psum
    hooks), so the addressing can never diverge between world-1 and
    mesh.  ``ffn``/``out_proj`` override the default TP seams (the
    w8a8 serving hooks ride here — same psum count, quantized
    contraction)."""
    from triton_dist_tpu.serve.engine import _paged_decode_forward

    return _paged_decode_forward(
        params, pools, tables, kv_lens, token, active, cfg=cfg,
        page=page, impl=impl, interpret=interpret,
        fwd_cfg=_local_cfg(cfg, world),
        ffn=ffn or functools.partial(_tp_ffn, axis=axis),
        out_proj=out_proj or functools.partial(_tp_out_proj, axis=axis))


def tp_paged_verify_shard(params, pools, tables, kv_lens, chunk, active,
                          *, cfg, page, axis, world, impl, interpret,
                          ffn=None, out_proj=None):
    """Head-sharded twin of ``engine._paged_verify_forward`` — the
    multi-token verify under shard_map; like the decode twin, the
    engine's own forward with the TP seams supplied."""
    from triton_dist_tpu.serve.engine import _paged_verify_forward

    return _paged_verify_forward(
        params, pools, tables, kv_lens, chunk, active, cfg=cfg,
        page=page, impl=impl, interpret=interpret,
        fwd_cfg=_local_cfg(cfg, world),
        ffn=ffn or functools.partial(_tp_ffn, axis=axis),
        out_proj=out_proj or functools.partial(_tp_out_proj, axis=axis))


def _rebase_local(ids, *, axis, world, num_blocks):
    """THE global→local block-id rebase of the seq layout, shared by
    every per-shard body that touches the pools: rank ``r`` owns global
    blocks ``[r*nb_loc, (r+1)*nb_loc)``; returns ``(mine, local)``
    where foreign/padded ids (another rank's blocks, the global null)
    map to local row 0 — the rank's own reserved null, so a non-owner's
    write or copy degenerates to a null self-touch exactly like an
    inactive row's."""
    nb_loc = num_blocks // world
    lo = jax.lax.axis_index(axis) * nb_loc
    mine = (ids >= lo) & (ids < lo + nb_loc)
    return mine, jnp.where(mine, ids - lo, 0)


def sp_paged_decode_shard(params, pools, tables, kv_lens, token, active,
                          *, cfg, page, axis, world, num_blocks,
                          n_pages_max, impl, interpret):
    """Sequence-sharded twin of ``engine._paged_decode_forward``:
    weights replicated, pools sharded on the BLOCK axis — rank ``r``
    holds global blocks ``[r*nb_loc, (r+1)*nb_loc)``, which the
    partitioned :class:`serve.block_manager.BlockManager` dedicates to
    the logical pages of rank ``r``'s sequence span.  The block table
    is global; each rank slices its span and rebases the ids to local
    pool rows (foreign/padded entries — including another rank's
    blocks and the global null — map to local row 0, the rank's own
    reserved null).  Attention goes through
    ``sp_gqa_decode_paged_shard`` (local lengths + LSE combine), so
    the returned logits are replicated.  Quantized pools ride through
    unchanged: ``_scatter_kv`` and ``_pool_views`` are both
    dict-aware, and the per-page scales feed the combine's dequant."""
    from triton_dist_tpu.serve.engine import (
        _page_slots,
        _pool_views,
        _scatter_kv,
    )

    n_loc = n_pages_max // world
    inc = active.astype(kv_lens.dtype)

    # The next write's physical slot, rebased: only the owning rank
    # writes the real row; everyone else's write redirects to ITS null
    # (local row 0) exactly like an inactive row.
    pool_row_g, in_page = _page_slots(tables, kv_lens, active, page=page)
    mine, pool_row = _rebase_local(pool_row_g, axis=axis, world=world,
                                   num_blocks=num_blocks)
    mine = mine & active
    pool_row = jnp.where(mine, pool_row, 0)
    in_page = jnp.where(mine, in_page, 0)

    def write_kv(li, pool, k, v):
        return _scatter_kv(pool, k, v, pool_row, in_page)

    me = jax.lax.axis_index(axis)
    lt = jax.lax.dynamic_slice_in_dim(tables, me * n_loc, n_loc, axis=1)
    _, lt = _rebase_local(lt, axis=axis, world=world,
                          num_blocks=num_blocks)

    def attend(li, q, pool):
        kq, vq, ks, vs = _pool_views(pool)
        return sp_gqa_decode_paged_shard(
            q, kq, vq, lt, kv_lens + inc, axis=axis,
            impl=impl, interpret=interpret, soft_cap=cfg.attn_soft_cap,
            window=cfg.attn_window, k_scale=ks, v_scale=vs)

    return _token_forward(params, pools, token, kv_lens, cfg=cfg,
                          write_kv=write_kv, attend=attend)


def tp_paged_decode_horizon_shard(params, pools, tables, kv_lens, token,
                                  active, eos_done, limits, counts,
                                  base_keys, temps, top_ks, top_ps,
                                  greedy, eos_ids, *, H, all_greedy, cfg,
                                  page, axis, world, impl, interpret,
                                  ffn=None, out_proj=None):
    """The fused decode horizon under shard_map (heads): the engine's
    ``_paged_decode_horizon`` scan with the TP per-step forward swapped
    in — on-device sampling and every carry stay replicated, so the
    token bursts are bit-identical to the world-1 scan."""
    from triton_dist_tpu.serve.engine import _paged_decode_horizon

    fwd = functools.partial(tp_paged_decode_shard, cfg=cfg, page=page,
                            axis=axis, world=world, impl=impl,
                            interpret=interpret, ffn=ffn,
                            out_proj=out_proj)
    return _paged_decode_horizon(
        params, pools, tables, kv_lens, token, active, eos_done, limits,
        counts, base_keys, temps, top_ks, top_ps, greedy, eos_ids, H=H,
        all_greedy=all_greedy, cfg=cfg, page=page, impl=impl,
        interpret=interpret, decode_fwd=fwd)


def sp_paged_decode_horizon_shard(params, pools, tables, kv_lens, token,
                                  active, eos_done, limits, counts,
                                  base_keys, temps, top_ks, top_ps,
                                  greedy, eos_ids, *, H, all_greedy, cfg,
                                  page, axis, world, num_blocks,
                                  n_pages_max, impl, interpret):
    """The fused decode horizon over sequence-sharded pools: the same
    scan with the SP per-step forward (local spans + LSE combine)."""
    from triton_dist_tpu.serve.engine import _paged_decode_horizon

    fwd = functools.partial(sp_paged_decode_shard, cfg=cfg, page=page,
                            axis=axis, world=world,
                            num_blocks=num_blocks,
                            n_pages_max=n_pages_max, impl=impl,
                            interpret=interpret)
    return _paged_decode_horizon(
        params, pools, tables, kv_lens, token, active, eos_done, limits,
        counts, base_keys, temps, top_ks, top_ps, greedy, eos_ids, H=H,
        all_greedy=all_greedy, cfg=cfg, page=page, impl=impl,
        interpret=interpret, decode_fwd=fwd)


def tp_spec_round_shard(params, draft_params, pools, dcaches, tables,
                        kv_lens, active, done, last_logits, dlast_logits,
                        counts, limits, k_rows, base_keys, temps, top_ks,
                        top_ps, greedy, eos_ids, *, K, all_greedy, cfg,
                        dcfg, page, axis, world, impl, interpret,
                        dimpl, dinterpret):
    """The whole fused speculative round under shard_map (heads): the
    target's verify + decode legs run head-sharded TP, the draft steps
    REPLICATED per rank (its slot-indexed batch caches are host-managed
    whole-batch state — sharding them would put the accept chain's
    inputs behind a gather), and the seeded accept/sampling math runs on
    replicated logits — bit-identical emissions per rank."""
    from triton_dist_tpu.serve.engine import (
        _draft_decode_forward,
        _spec_round_fused,
    )

    decode_fwd = functools.partial(tp_paged_decode_shard, cfg=cfg,
                                   page=page, axis=axis, world=world,
                                   impl=impl, interpret=interpret)
    verify_fwd = functools.partial(tp_paged_verify_shard, cfg=cfg,
                                   page=page, axis=axis, world=world,
                                   impl=impl, interpret=interpret)
    draft_step = functools.partial(_draft_decode_forward, cfg=dcfg,
                                   impl=dimpl, interpret=dinterpret)
    return _spec_round_fused(
        params, draft_params, pools, dcaches, tables, kv_lens, active,
        done, last_logits, dlast_logits, counts, limits, k_rows,
        base_keys, temps, top_ks, top_ps, greedy, eos_ids, K=K,
        all_greedy=all_greedy, cfg=cfg, page=page, impl=impl,
        interpret=interpret, draft_step=draft_step,
        decode_fwd=decode_fwd, verify_fwd=verify_fwd)


def tp_chunk_forward_shard(params, chunk, caches, prefix_len, n_valid, *,
                           cfg, extent, axis, world, impl, interpret,
                           quantized=False, ffn=None, out_proj=None):
    """Head-sharded chunked prefill: ``generate._chunk_forward`` with
    the local-head cfg and the TP reduction hooks — each rank computes
    its head columns of the chunk's K/V into its shard of the prefill
    scratch, attention runs per-head over the local scratch, and the
    out-proj/FFN seams ``psum``.  ``mesh``/``axis`` stay None inside:
    the per-rank scratch is head-local, never sequence-sharded.
    ``quantized`` writes the chunk's K/V into int8+scale scratch
    (the rank's local heads quantize independently — same per-(head,
    position) absmax math as world-1, so the pages are bit-identical)."""
    return _chunk_forward(
        params, chunk, caches, prefix_len, cfg=_local_cfg(cfg, world),
        quantized=quantized,
        ffn=ffn or functools.partial(_tp_ffn, axis=axis),
        out_proj=out_proj or functools.partial(_tp_out_proj, axis=axis),
        extent=extent, n_valid=n_valid, impl=impl, interpret=interpret)


def rep_chunk_forward_shard(params, chunk, caches, prefix_len, n_valid,
                            *, cfg, extent, impl, interpret,
                            quantized=False):
    """Replicated chunked prefill (the seq layout, and the draft model
    under a heads mesh): every rank runs the identical world-1 chunk
    forward — prefill compute does not shard here, only the page
    scatter downstream does (kv_shard='seq' exists for the DECODE
    attention scaling; docs/serving.md records the trade)."""
    return _chunk_forward(params, chunk, caches, prefix_len, cfg=cfg,
                          quantized=quantized, extent=extent,
                          n_valid=n_valid, impl=impl, interpret=interpret)


# -- page scatter / gather / COW over sharded pools -------------------------


def sp_fill_pool_pages_shard(pools, scratch, ids, *, page, axis, world,
                             num_blocks):
    """Sequence-sharded page scatter: ``ids`` are GLOBAL block ids per
    scratch page; each rank rebases its own ids to local pool rows and
    scatters only those pages — foreign and padded entries land in the
    rank's local null (row 0), exactly where world-1 scatters its
    padding."""
    from triton_dist_tpu.serve.engine import _fill_pool_pages

    _, loc = _rebase_local(ids, axis=axis, world=world,
                           num_blocks=num_blocks)
    return _fill_pool_pages(pools, scratch, loc, page=page)


def sp_gather_pool_pages_shard(pools, ids, *, page, axis, world,
                               num_blocks):
    """Sequence-sharded page gather (the warm-prefix / drain read-back):
    each rank gathers its own pages into the replicated scratch layout,
    zeroes the rows it does not own, and a ``psum`` assembles the full
    scratch — every row has exactly one owner, so the sum is exact
    (adding zeros never perturbs floats)."""
    from triton_dist_tpu.serve.engine import _gather_pool_pages

    mine, loc = _rebase_local(ids, axis=axis, world=world,
                              num_blocks=num_blocks)
    sc = _gather_pool_pages(pools, loc, page=page)
    rows = jnp.repeat(mine, page)

    def _own(x):
        # scratch row axis is 2 for both layouts: [1,H,S,D] pages and
        # [1,H,S] per-page scales — broadcast the ownership mask over
        # whatever trails it (int8 pages psum exactly: one owner per
        # row, everyone else contributes true zeros)
        r = rows.reshape((1, 1, -1) + (1,) * (x.ndim - 3))
        return jnp.where(r, x, jnp.zeros((), x.dtype))

    sc = jax.tree_util.tree_map(_own, sc)
    return jax.lax.psum(sc, axis)


def sp_copy_pool_block_shard(pools, src, dst, *, axis, world, num_blocks):
    """Sequence-sharded COW page copy: the partitioned allocator keeps
    both halves of a split in one partition, so exactly the owning rank
    copies (everyone else degenerates to a null→null self-copy)."""
    from triton_dist_tpu.serve.engine import _copy_pool_block

    _, s = _rebase_local(src, axis=axis, world=world,
                         num_blocks=num_blocks)
    # the allocator keeps both halves of a split in one partition, so
    # dst rebases under the same ownership (foreign ranks get 0 -> 0)
    _, d = _rebase_local(dst, axis=axis, world=world,
                         num_blocks=num_blocks)
    return _copy_pool_block(pools, s, d)


# ---------------------------------------------------------------------------
# ShardedProgram — jit(shard_map) + canonical argument placement
# ---------------------------------------------------------------------------


def _place(x, sharding):
    """Commit ``x`` onto ``sharding`` unless it already carries it —
    the one-signature-per-program guarantee (module docstring).
    Tracers pass through: under a re-trace (the jaxpr auditor replaying
    a captured signature) placement is a runtime concern and a tracer
    carries no sharding to inspect."""
    if isinstance(x, jax.core.Tracer):
        return x
    if isinstance(x, jax.Array) and x.sharding == sharding:
        return x
    return jax.device_put(x, sharding)


def _shardings_of(mesh, spec_tree):
    """PartitionSpec tree → NamedSharding tree (specs are pytrees of
    tuples, so they must be treated as leaves)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


class ShardedProgram:
    """One engine device program on a mesh: ``jax.jit(jax.shard_map(
    body))`` with per-argument canonical placement and a bounded
    static-kwargs ladder.

    - Positional args are pytrees matched leaf-wise against
      ``in_specs``; every leaf is ``device_put`` to its declared
      ``NamedSharding`` unless already there — host-built and
      device-carried calls hit the SAME executable (the PR-7 cache-fork
      fix; module docstring).
    - Keyword args are STATIC trace parameters (the horizon's ``H``,
      the spec round's ``K``, ...): each distinct combination memoizes
      one jitted closure, exactly like ``static_argnames`` — and
      ``_cache_size()`` sums the inner caches so ``CountingJit``'s
      hit/miss accounting (and warmup's fixed-point test) keep working
      unchanged.
    - ``donate_argnums`` applies to the placed arrays; the engine
      already reassigns donated carries from the outputs.
    - ``timer`` (optional ``(label, ms)`` callable, the
      ``jit_cache.CountingJit`` protocol): every call's wall time —
      placement included, it is part of what the program costs — is
      reported under ``name`` suffixed with the ``timed_statics``
      kwargs' values (``decode_horizon[H=8]``).  The engine wires its
      CountingJit wrapper's timer instead (one seam for mesh and
      world-1 programs); this hook serves direct ShardedProgram users.
    """

    def __init__(self, body, mesh, in_specs, out_specs, *,
                 donate_argnums=(), name=None, timer=None,
                 timed_statics=()):
        self.body = body
        self.mesh = mesh
        self.in_specs = tuple(in_specs)
        self.out_specs = out_specs
        self.donate_argnums = tuple(donate_argnums)
        self.name = name or getattr(body, "__name__", "sharded_program")
        self.timer = timer
        self.timed_statics = tuple(timed_statics)
        self._placements = tuple(_shardings_of(mesh, s)
                                 for s in self.in_specs)
        self._jits: dict = {}
        #: statics-key -> abstracted args of the first call per rung
        #: (the jaxpr auditor's re-trace seed, like CountingJit's)
        self.captured: dict = {}

    def _prog(self, statics: tuple):
        prog = self._jits.get(statics)
        if prog is None:
            fn = (functools.partial(self.body, **dict(statics))
                  if statics else self.body)
            prog = jax.jit(
                jax.shard_map(fn, mesh=self.mesh, in_specs=self.in_specs,
                              out_specs=self.out_specs, check_vma=False),
                donate_argnums=self.donate_argnums)
            self._jits[statics] = prog
        return prog

    def place(self, i: int, value):
        """Canonical placement of argument ``i`` (exposed so the engine
        can pre-place long-lived carries like the pools at init)."""
        return jax.tree_util.tree_map(_place, value, self._placements[i])

    def __call__(self, *args, **statics):
        timer = self.timer
        before = self._cache_size() if timer is not None else 0
        t0 = time.perf_counter() if timer is not None else 0.0
        placed = tuple(
            jax.tree_util.tree_map(_place, a, p)
            for a, p in zip(args, self._placements))
        key = tuple(sorted(statics.items()))
        if key not in self.captured and \
                len(self.captured) < jit_cache.MAX_CAPTURED_SIGNATURES:
            self.captured[key] = jit_cache.abstract_signature(
                placed, dict(statics))
        out = self._prog(key)(*placed)
        # compile calls (cache grew) stay out of the distributions —
        # the same rule as CountingJit: stalls are compile accounting,
        # not program wall time
        if timer is not None and self._cache_size() == before:
            label = self.name
            for k in self.timed_statics:
                v = statics.get(k)
                if v is not None:
                    label = f"{label}[{k}={v}]"
            timer(label, (time.perf_counter() - t0) * 1e3)
        return out

    def _cache_size(self) -> int:
        # CountingJit keys its miss accounting on this (a fresh static
        # rung AND a fresh signature within a rung both count — the
        # same events a plain jit's cache growth reports).
        return sum(p._cache_size() for p in self._jits.values())


class MeshChunkJit:
    """The mesh chunk-prefill program behind ``Generator._chunk_jit``'s
    call convention (``(params, buf, scratch, prefix, *, quantized,
    extent, n_valid)`` with ``quantized``/``extent`` static and
    ``n_valid`` traced): one :class:`ShardedProgram` per extent rung,
    ``n_valid`` folded into the positional args.  ``quantized`` is a
    CONSTRUCTION property here, not a per-call rung: the pool dtype is
    engine geometry, the chunk bodies are built for exactly one dtype,
    and a call asking for the other is a wiring bug worth an assert."""

    def __init__(self, maker, *, quantized=False):
        self._maker = maker     # extent -> ShardedProgram
        self._progs: dict = {}
        self._quantized = bool(quantized)

    def __call__(self, params, buf, scratch, prefix, *, quantized,
                 extent, n_valid):
        assert quantized == self._quantized, (
            "mesh chunk prefill was built for "
            f"quantized={self._quantized}; called with {quantized}")
        prog = self._progs.get(extent)
        if prog is None:
            prog = self._maker(extent)
            self._progs[extent] = prog
        return prog(params, buf, scratch, prefix, n_valid)

    def _cache_size(self) -> int:
        return sum(p._cache_size() for p in self._progs.values())


# ---------------------------------------------------------------------------
# Program construction (the engine's mesh-mode __init__ calls this)
# ---------------------------------------------------------------------------


def collective_seams(cfg, *, kv_shard: str, draft_cfg=None) -> dict:
    """Declared collective seams per engine program — the contract the
    jaxpr auditor (``analysis/jaxpr_audit.py``) enforces: any
    collective primitive a program traces that is NOT declared here is
    a violation, and declared counts must match exactly.

    ``kv_shard="heads"`` (Megatron TP): the ONLY collectives in any
    forward are the two row-parallel ``psum``s per layer (attn
    out-proj, ``_tp_out_proj``; FFN down, ``_tp_ffn``) — 2 x n_layers
    per forward, nothing in per-rank attention, sampling, or the page
    programs.  ``kv_shard="seq"`` (SP flash-decode): one inter-rank
    LSE-combine gather per layer in the decode forwards
    (``sp_gqa_decode_paged_shard``), a replicated chunk prefill (no
    collectives), and one ``psum`` in the page gather
    (``sp_gather_pool_pages_shard`` zeroes unowned rows and psum-
    assembles the full gather).  Spec rounds chain draft (replicated —
    collective-free) and target forwards: K+1 target forwards for the
    K-step draft scan + verify + closing decode... the spec round's
    exact chain is 2 target forwards traced (verify + closing decode,
    the draft scan is replicated), so 2x the per-forward seam count.
    """
    n = cfg.n_layers
    if kv_shard == "heads":
        fwd = {"psum": 2 * n}
        seams = {
            "paged_decode": dict(fwd),
            "paged_verify": dict(fwd),
            "decode_horizon": dict(fwd),
            "prefill_chunk": dict(fwd),
            # page scatter/gather/COW move KV bytes inside each rank's
            # own head shard: collective-free.
            "fill_pages": {}, "load_pages": {}, "cow_copy": {},
            # spec round: draft scan replicated (collective-free),
            # verify + closing decode are 2 target forwards.
            "spec_round": {"psum": 2 * (2 * n)},
            "draft_tail_step": {},
            "draft_prefill": {}, "draft_join": {}, "draft_step": {},
            "draft_fill_pages": {}, "draft_load_pages": {},
        }
        return seams
    if kv_shard == "seq":
        fwd = {"all_gather": n}
        return {
            "paged_decode": dict(fwd),
            "paged_verify": dict(fwd),
            "decode_horizon": dict(fwd),
            # seq-mode chunked prefill computes replicated (ROADMAP #1
            # follow-up): only the page scatter shards.
            "prefill_chunk": {},
            "fill_pages": {},
            "load_pages": {"psum": 1},
            "cow_copy": {},
            "spec_round": {"all_gather": 2 * n},
            "draft_tail_step": {},
            "draft_prefill": {}, "draft_join": {}, "draft_step": {},
            "draft_fill_pages": {}, "draft_load_pages": {},
        }
    raise ValueError(f"unknown kv_shard {kv_shard!r}")


def replicated_like(tree):
    """All-``P()`` spec tree matching ``tree``'s structure."""
    return jax.tree_util.tree_map(lambda _: P(), tree)


def build_programs(*, mesh, tp_axis, kv_shard, cfg, params, page_size,
                   num_blocks, n_pages_max, impl, interpret,
                   horizon: int, draft=None, draft_params=None,
                   spec_fused: bool = False,
                   prefix_cache: bool = False,
                   kv_quant: bool = False,
                   w8a8: bool = False) -> dict:
    """All mesh device programs for one engine, keyed by the engine's
    program names (``paged_decode``, ``paged_verify``, ``fill_pages``,
    ``load_pages``, ``cow_copy``, ``decode_horizon``, ``prefill_chunk``
    — plus the draft family on spec engines).  Shapes/donation mirror
    the world-1 programs exactly, so warmup, metrics, and the step loop
    need no mesh-specific branches past construction.

    ``kv_quant`` swaps every pool/scratch spec for the dict-structured
    ``{"q": spec, "s": spec}`` twin — the SAME PartitionSpec legally
    covers both planes (heads shards axis 1 = Hkv of the 4D pages and
    the 3D scales alike; seq shards the shared block axis 0), and the
    forward/page bodies are already dict-aware, so the program set and
    its collective seams are unchanged.  ``w8a8`` (heads only — the
    engine rejects it elsewhere) swaps ``param_specs`` for
    ``w8a8_serve_param_specs`` and the TP reduction seams for the
    quantized serving hooks: same one-psum-per-seam shape, int8
    contraction inside."""
    axis = tp_axis
    world = int(mesh.shape[axis])
    heads = kv_shard == "heads"
    pool_spec = P(None, axis) if heads else P(axis)
    kv_spec = ({"q": pool_spec, "s": pool_spec} if kv_quant
               else pool_spec)
    pools_specs = [(kv_spec, kv_spec)] * cfg.n_layers
    if heads:
        if w8a8:
            from triton_dist_tpu.models.llama_w8a8 import (
                w8a8_serve_ffn,
                w8a8_serve_out_proj,
                w8a8_serve_param_specs,
            )

            p_specs = w8a8_serve_param_specs(cfg, axis)
            hooks = {
                "ffn": functools.partial(
                    w8a8_serve_ffn, axis=axis, impl=impl,
                    interpret=interpret),
                "out_proj": functools.partial(
                    w8a8_serve_out_proj, axis=axis, impl=impl,
                    interpret=interpret),
            }
        else:
            p_specs = param_specs(cfg, axis)
            hooks = {}
    else:
        p_specs = replicated_like(params)
    scratch_spec = P(None, axis) if heads else P()
    sc_spec = ({"q": scratch_spec, "s": scratch_spec} if kv_quant
               else scratch_spec)

    out = {"pool_spec": pool_spec, "params_specs": p_specs, "world": world}

    if heads:
        decode_body = functools.partial(
            tp_paged_decode_shard, cfg=cfg, page=page_size, axis=axis,
            world=world, impl=impl, interpret=interpret, **hooks)
        verify_body = functools.partial(
            tp_paged_verify_shard, cfg=cfg, page=page_size, axis=axis,
            world=world, impl=impl, interpret=interpret, **hooks)
        horizon_body = functools.partial(
            tp_paged_decode_horizon_shard, cfg=cfg, page=page_size,
            axis=axis, world=world, impl=impl, interpret=interpret,
            **hooks)
        fill_body = functools.partial(
            __import_engine()._fill_pool_pages, page=page_size)
        load_body = functools.partial(
            __import_engine()._gather_pool_pages, page=page_size)
        cow_body = __import_engine()._copy_pool_block
        chunk_body = functools.partial(
            tp_chunk_forward_shard, cfg=cfg, axis=axis, world=world,
            impl=impl, interpret=interpret, quantized=kv_quant, **hooks)
    else:
        decode_body = functools.partial(
            sp_paged_decode_shard, cfg=cfg, page=page_size, axis=axis,
            world=world, num_blocks=num_blocks, n_pages_max=n_pages_max,
            impl=impl, interpret=interpret)
        verify_body = None  # rejected at construction (spec x seq)
        horizon_body = functools.partial(
            sp_paged_decode_horizon_shard, cfg=cfg, page=page_size,
            axis=axis, world=world, num_blocks=num_blocks,
            n_pages_max=n_pages_max, impl=impl, interpret=interpret)
        fill_body = functools.partial(
            sp_fill_pool_pages_shard, page=page_size, axis=axis,
            world=world, num_blocks=num_blocks)
        load_body = functools.partial(
            sp_gather_pool_pages_shard, page=page_size, axis=axis,
            world=world, num_blocks=num_blocks)
        cow_body = functools.partial(
            sp_copy_pool_block_shard, axis=axis, world=world,
            num_blocks=num_blocks)
        chunk_body = functools.partial(
            rep_chunk_forward_shard, cfg=cfg, impl=impl,
            interpret=interpret, quantized=kv_quant)

    # (params, pools, tables, kv_lens, token/chunk, active)
    fwd_in = (p_specs, pools_specs, P(), P(), P(), P())
    out["paged_decode"] = ShardedProgram(
        decode_body, mesh, fwd_in, (pools_specs, P()),
        donate_argnums=(1,))
    if verify_body is not None:
        out["paged_verify"] = ShardedProgram(
            verify_body, mesh, fwd_in, (pools_specs, P()),
            donate_argnums=(1,))
    if horizon > 1:
        out["decode_horizon"] = ShardedProgram(
            horizon_body, mesh,
            (p_specs, pools_specs) + (P(),) * 13,
            (pools_specs,) + (P(),) * 6, donate_argnums=(1,))
    out["fill_pages"] = ShardedProgram(
        fill_body, mesh,
        (pools_specs, [(sc_spec, sc_spec)] * cfg.n_layers, P()),
        pools_specs, donate_argnums=(0,))
    out["load_pages"] = ShardedProgram(
        load_body, mesh, (pools_specs, P()),
        [(sc_spec, sc_spec)] * cfg.n_layers)
    out["cow_copy"] = ShardedProgram(
        cow_body, mesh, (pools_specs, P(), P()), pools_specs,
        donate_argnums=(0,))

    def make_chunk(extent: int) -> ShardedProgram:
        return ShardedProgram(
            functools.partial(chunk_body, extent=extent), mesh,
            (p_specs, P(),
             [(sc_spec, sc_spec)] * cfg.n_layers, P(), P()),
            ([(sc_spec, sc_spec)] * cfg.n_layers, P()),
            donate_argnums=(2,))

    out["prefill_chunk"] = MeshChunkJit(make_chunk, quantized=kv_quant)

    if draft is not None and spec_fused:
        dcfg = draft.cfg
        d_specs = replicated_like(draft_params)
        dpools_specs = [(P(), P())] * dcfg.n_layers
        spec_body = functools.partial(
            tp_spec_round_shard, cfg=cfg, dcfg=dcfg, page=page_size,
            axis=axis, world=world, impl=impl, interpret=interpret,
            dimpl=draft.attn.ctx.impl, dinterpret=draft.attn.ctx.interpret)
        out["spec_round"] = ShardedProgram(
            spec_body, mesh,
            (p_specs, d_specs, pools_specs, dpools_specs)
            + (P(),) * 15,
            (pools_specs, dpools_specs) + (P(),) * 9,
            donate_argnums=(2, 3))
        tail_body = functools.partial(
            __import_engine()._draft_decode_forward, cfg=dcfg,
            impl=draft.attn.ctx.impl, interpret=draft.attn.ctx.interpret)
        out["draft_tail_step"] = ShardedProgram(
            tail_body, mesh, (d_specs, dpools_specs, P(), P(), P()),
            (dpools_specs, P(), P()), donate_argnums=(1,))
        out["draft_join"] = ShardedProgram(
            __import_engine()._splice_draft_rows, mesh,
            (dpools_specs, P(), P(),
             [(P(), P())] * dcfg.n_layers, P(), P(), P()),
            (dpools_specs, P(), P()), donate_argnums=(0, 1, 2))
        dchunk_body = functools.partial(
            rep_chunk_forward_shard, cfg=dcfg,
            impl=draft.attn.ctx.impl, interpret=draft.attn.ctx.interpret)

        def make_draft_chunk(extent: int) -> ShardedProgram:
            return ShardedProgram(
                functools.partial(dchunk_body, extent=extent), mesh,
                (d_specs, P(), [(P(), P())] * dcfg.n_layers, P(), P()),
                ([(P(), P())] * dcfg.n_layers, P()), donate_argnums=(2,))

        out["draft_prefill"] = MeshChunkJit(make_draft_chunk)
        if prefix_cache:
            out["draft_fill_pages"] = ShardedProgram(
                functools.partial(__import_engine()._fill_pool_pages,
                                  page=page_size), mesh,
                (dpools_specs, [(P(), P())] * dcfg.n_layers, P()),
                dpools_specs, donate_argnums=(0,))
            out["draft_load_pages"] = ShardedProgram(
                functools.partial(__import_engine()._gather_pool_pages,
                                  page=page_size), mesh,
                (dpools_specs, P()), [(P(), P())] * dcfg.n_layers)
    return out


def __import_engine():
    """Deferred engine import: engine.py imports this module inside its
    constructor, so a module-level back-import would be circular."""
    from triton_dist_tpu.serve import engine

    return engine
