"""Serving metrics: per-request latencies + engine-level gauges.

Per request: TTFT (arrival → first emitted token), inter-token latencies,
queue wait (arrival → first scheduled).  Per engine step: queue depth,
running batch occupancy, KV-block utilization; counters for preemptions,
prefill tokens, decode/verify passes.  Compilation observability: the
engine registers its ``jit_cache.CountingJit``-wrapped programs here, so
trace-cache hits/misses, cumulative compile-stall time, warmup coverage,
and the process-wide ``cached_shard_jit`` stats all land in
:meth:`ServeMetrics.summary` under ``"compilation"`` (docs/serving.md
"Reading the compile metrics").

Export rides the existing observability path (``runtime/dump.py``): with
``TDT_DUMP_IR=<dir>`` set, :meth:`ServeMetrics.maybe_dump` writes
``<dir>/<name>.json`` next to the kernel IR dumps — one switch arms both.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional

from triton_dist_tpu.runtime import dump


@dataclass
class RequestMetrics:
    """Timestamps (engine clock) and derived latencies for one request."""

    arrival_time: float
    first_scheduled_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    token_times: list[float] = field(default_factory=list)
    n_preemptions: int = 0
    # prefix cache (docs/serving.md "Prefix caching"): prompt tokens
    # covered by shared cached blocks at this request's admission — a
    # warm request skips that much prefill compute, so its TTFT is the
    # number the cache exists to collapse
    cached_prefix_tokens: int = 0

    def on_scheduled(self, now: float) -> None:
        if self.first_scheduled_time is None:
            self.first_scheduled_time = now

    def on_token(self, now: float) -> None:
        if self.first_token_time is None:
            self.first_token_time = now
        self.token_times.append(now)

    def burst_times(self, now: float, n: int, step_s: float) -> list[float]:
        """Timestamps for ``n`` tokens committed in ONE decode-horizon
        drain: spaced backwards from ``now`` by the DEVICE step cadence
        (``step_s`` = horizon wall time / device steps) instead of
        collapsing onto the drain instant.  Burst commits would otherwise
        read as ITL 0 inside a burst and a full horizon between bursts —
        the per-token latency a client streaming from the engine actually
        sees is the device's, and this reconstructs it."""
        return [now - i * step_s for i in range(n - 1, -1, -1)]

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token (arrival → first emission)."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    @property
    def queue_time(self) -> Optional[float]:
        if self.first_scheduled_time is None:
            return None
        return self.first_scheduled_time - self.arrival_time

    @property
    def inter_token_latencies(self) -> list[float]:
        t = self.token_times
        return [b - a for a, b in zip(t, t[1:])]

    @property
    def mean_itl(self) -> Optional[float]:
        itl = self.inter_token_latencies
        return sum(itl) / len(itl) if itl else None

    def to_dict(self) -> dict:
        return {
            "arrival_time": self.arrival_time,
            "ttft": self.ttft,
            "queue_time": self.queue_time,
            "mean_itl": self.mean_itl,
            "n_tokens": len(self.token_times),
            "n_preemptions": self.n_preemptions,
            "cached_prefix_tokens": self.cached_prefix_tokens,
            "finish_time": self.finish_time,
        }


@dataclass
class ServeMetrics:
    """Engine-level counters + per-step gauge series."""

    # counters
    steps: int = 0
    decode_steps: int = 0
    verify_rounds: int = 0
    prefill_tokens: int = 0
    preemptions: int = 0
    completed: int = 0
    # decode-loop dispatch accounting (docs/serving.md "Decode horizon"):
    # how many device dispatches and host sync points the decode path
    # paid per emitted token.  At horizon H=1 every token costs one
    # dispatch + one sync; the fused horizon amortizes both — the
    # dispatches_per_token quotient is THE metric the horizon exists to
    # shrink.
    decode_tokens: int = 0        # tokens committed by the decode loop
    dispatches: int = 0           # decode-path device dispatches
    host_syncs: int = 0           # decode-path host sync points
    # failure-containment counters (docs/serving.md "Failure
    # containment"): every non-healthy retirement and every recovery
    # action is a counter, so overload and poison traffic are visible
    # in the same summary as latency.
    shed: int = 0                 # submit() rejections (queue at bound)
    deadline_expired: int = 0     # WAITING/PREFILL TTL sweeps
    quarantined: int = 0          # requests retired FinishReason.ERROR
    callback_errors: int = 0      # on_token raised; callback disabled
    forward_retries: int = 0      # batched-forward retry attempts
    forward_bisections: int = 0   # batch splits isolating a poison row
    watchdog_trips: int = 0       # step watchdog timeouts (re-raised)
    spec_bailouts: int = 0        # speculative rounds latched off
    # speculative-decoding counters (docs/serving.md "Speculative
    # decoding"): acceptance is the number that decides whether
    # speculation pays — proposed/accepted feed the overall and rolling
    # rates, chosen_k histograms the adaptive per-row depth, and
    # spec_tokens/spec_dispatches give tokens-per-dispatch for the fused
    # round alone (the ISSUE-7 guardrail: >= plain fused decode).
    spec_rounds: int = 0          # fused rounds that emitted something
    spec_proposed: int = 0        # draft tokens proposed (per-row budget)
    spec_accepted: int = 0        # proposals the target's stream matched
    spec_tokens: int = 0          # tokens committed by spec rounds
    spec_dispatches: int = 0      # fused spec-round dispatches
    spec_recent: list = field(default_factory=list, repr=False)
    spec_chosen_k: dict = field(default_factory=dict)
    draft_prefix_skipped_tokens: int = 0  # draft prefill skipped via the
    #                               draft-side page cache (warm admits)
    # retirements by FinishReason.value
    finish_reasons: dict = field(default_factory=dict)
    # crash-recovery counters (docs/serving.md "Crash recovery"):
    # snapshot latency + journal overhead on the serving side, restore
    # provenance on the resume side (how much state came back in place
    # vs through exact recompute).
    snapshots: int = 0            # engine.snapshot() captures
    snapshot_ms_last: float = 0.0
    snapshot_ms_total: float = 0.0
    journal_records: int = 0      # journal appends by this engine
    journal_bytes: int = 0
    journal_rotations: int = 0    # compactions at snapshot barriers
    restores: int = 0             # 1 on an engine built by restore()
    restored_in_place: int = 0    # requests resumed with live KV
    restored_requeued: int = 0    # requests re-queued for recompute
    restored_tokens: int = 0      # journal tokens carried across
    # prefix-cache counters (docs/serving.md "Prefix caching"): engine-
    # side admission hits; the block-level gauges (refcounts, cache
    # tier, COW/eviction counts) live on the attached BlockManager and
    # merge into summary()["prefix_cache"] via attach_block_manager().
    prefix_hits: int = 0          # admissions mapping >= 1 shared block
    prefix_hit_tokens: int = 0    # prompt tokens covered by shared blocks
    prefix_skipped_tokens: int = 0  # prefill tokens actually skipped
    block_manager: object = field(default=None, repr=False)
    # compilation observability: CountingJit wrappers the engine
    # registers (runtime/jit_cache.py) + warmup accounting
    compiled_fns: list = field(default_factory=list, repr=False)
    warmup_time: float = 0.0
    warmup_compiles: int = 0
    # per-step gauge series (appended by the engine each iteration)
    queue_depth: list[int] = field(default_factory=list)
    running: list[int] = field(default_factory=list)
    kv_utilization: list[float] = field(default_factory=list)
    # retired requests' metrics, keyed by request id
    requests: dict = field(default_factory=dict)

    def observe_step(self, *, queue_depth: int, running: int,
                     kv_utilization: float) -> None:
        self.steps += 1
        self.queue_depth.append(queue_depth)
        self.running.append(running)
        self.kv_utilization.append(kv_utilization)

    def observe_finish(self, request_id: str, rm: RequestMetrics,
                       reason=None) -> None:
        self.completed += 1
        self.requests[request_id] = rm
        if reason is not None:
            key = getattr(reason, "value", str(reason))
            self.finish_reasons[key] = self.finish_reasons.get(key, 0) + 1

    def failure_stats(self) -> dict:
        """The containment counters as one dict (summary()["failures"])."""
        return {
            "shed": self.shed,
            "deadline_expired": self.deadline_expired,
            "quarantined": self.quarantined,
            "callback_errors": self.callback_errors,
            "forward_retries": self.forward_retries,
            "forward_bisections": self.forward_bisections,
            "watchdog_trips": self.watchdog_trips,
            "spec_bailouts": self.spec_bailouts,
            "finish_reasons": dict(self.finish_reasons),
        }

    def observe_spec_row(self, proposed: int, accepted: int,
                         chosen_k: int) -> None:
        """One row's share of one fused speculative round (the engine
        calls this at each round's drain)."""
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.spec_recent.append((proposed, accepted))
        del self.spec_recent[:-64]
        self.spec_chosen_k[chosen_k] = \
            self.spec_chosen_k.get(chosen_k, 0) + 1

    def spec_stats(self) -> dict:
        """Speculative-decoding observability (summary()["spec"]):
        per-round proposed/accepted counters, the overall and ROLLING
        (last 64 row-rounds) acceptance rates, the chosen-k histogram
        the adaptive policy produced, and spec tokens-per-dispatch —
        the economics field the fused round exists to move."""
        rp = sum(p for p, _ in self.spec_recent)
        ra = sum(a for _, a in self.spec_recent)
        return {
            "rounds": self.spec_rounds,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "accept_rate": (self.spec_accepted / self.spec_proposed
                            if self.spec_proposed else 0.0),
            "rolling_accept_rate": (ra / rp if rp else 0.0),
            "chosen_k": dict(sorted(self.spec_chosen_k.items())),
            "spec_tokens": self.spec_tokens,
            "spec_dispatches": self.spec_dispatches,
            "spec_tokens_per_dispatch": (
                self.spec_tokens / self.spec_dispatches
                if self.spec_dispatches else 0.0),
            "bailouts": self.spec_bailouts,
            "draft_prefix_skipped_tokens": self.draft_prefix_skipped_tokens,
        }

    def recovery_stats(self) -> dict:
        """Snapshot/journal/restore counters (summary()["recovery"])."""
        return {
            "snapshots": self.snapshots,
            "snapshot_ms_last": self.snapshot_ms_last,
            "snapshot_ms_total": self.snapshot_ms_total,
            "journal_records": self.journal_records,
            "journal_bytes": self.journal_bytes,
            "journal_rotations": self.journal_rotations,
            "restores": self.restores,
            "restored_in_place": self.restored_in_place,
            "restored_requeued": self.restored_requeued,
            "restored_tokens": self.restored_tokens,
        }

    def attach_block_manager(self, bm) -> None:
        """Fold the block manager's prefix-cache gauges into
        :meth:`summary` (the engine calls this at construction)."""
        self.block_manager = bm

    def prefix_stats(self) -> dict:
        """Admission-level hit counters + block-level cache gauges +
        the warm/cold TTFT split (summary()["prefix_cache"]).  A warm
        request is one whose admission mapped >= 1 shared block;
        ``ttft_warm_over_cold`` is the ratio the cache exists to
        collapse (the bench gate holds it <= 0.35 for a shared-prompt
        workload)."""
        warm = [m.ttft for m in self.requests.values()
                if m.cached_prefix_tokens > 0 and m.ttft is not None]
        cold = [m.ttft for m in self.requests.values()
                if m.cached_prefix_tokens == 0 and m.ttft is not None]
        out = {
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_skipped_tokens": self.prefix_skipped_tokens,
            "warm_requests": len(warm),
            "cold_requests": len(cold),
            "mean_ttft_warm": sum(warm) / len(warm) if warm else None,
            "mean_ttft_cold": sum(cold) / len(cold) if cold else None,
            "ttft_warm_over_cold": (
                (sum(warm) / len(warm)) / (sum(cold) / len(cold))
                if warm and cold and sum(cold) > 0 else None),
        }
        if self.block_manager is not None:
            out.update(self.block_manager.prefix_stats())
        return out

    def decode_stats(self) -> dict:
        """The decode-loop dispatch economics (summary()["decode"]).
        ``dispatches_per_token`` is ~1/batch for per-token decode (one
        dispatch per STEP emits a token per active row) and ~1/(batch·H)
        on a steady fused-horizon batch — the horizon amortizes steps,
        the batch amortizes rows, and only the former is the decode
        horizon's doing; ``host_syncs`` counts the blocking device→host
        fetches the loop paid."""
        return {
            "decode_steps": self.decode_steps,
            "decode_tokens": self.decode_tokens,
            "dispatches": self.dispatches,
            "host_syncs": self.host_syncs,
            "tokens_per_dispatch": (self.decode_tokens / self.dispatches
                                    if self.dispatches else 0.0),
            "dispatches_per_token": (self.dispatches / self.decode_tokens
                                     if self.decode_tokens else 0.0),
        }

    # -- compilation observability ---------------------------------------

    def register_compiled(self, counter) -> None:
        """Track a ``jit_cache.CountingJit``-wrapped program; its
        hit/miss/compile-time counters appear in :meth:`summary` under
        ``compilation`` (and on the ``TDT_DUMP_IR`` dump path)."""
        self.compiled_fns.append(counter)

    @property
    def compile_misses(self) -> int:
        """Total trace-cache misses (compiles) across engine programs —
        the bounded-compilation tests watch this stay flat after
        ``engine.warmup()``."""
        return sum(c.misses for c in self.compiled_fns)

    def compile_stats(self) -> dict:
        """Per-program trace-cache counters + the process-wide
        ``cached_shard_jit`` memo stats (runtime/jit_cache.py)."""
        from triton_dist_tpu.runtime import jit_cache

        return {
            "programs": {c.name: c.stats() for c in self.compiled_fns},
            "total_misses": self.compile_misses,
            "total_hits": sum(c.hits for c in self.compiled_fns),
            "total_compile_time_s": sum(c.compile_time
                                        for c in self.compiled_fns),
            "warmup_time_s": self.warmup_time,
            "warmup_compiles": self.warmup_compiles,
            "cached_shard_jit": jit_cache.cache_stats(),
        }

    def summary(self) -> dict:
        """Aggregate view (what the CLI prints and maybe_dump writes)."""
        ttfts = [m.ttft for m in self.requests.values()
                 if m.ttft is not None]
        itls = [x for m in self.requests.values()
                for x in m.inter_token_latencies]
        return {
            "steps": self.steps,
            "decode_steps": self.decode_steps,
            "verify_rounds": self.verify_rounds,
            "prefill_tokens": self.prefill_tokens,
            "preemptions": self.preemptions,
            "completed": self.completed,
            "max_queue_depth": max(self.queue_depth, default=0),
            "mean_running": (sum(self.running) / len(self.running)
                             if self.running else 0.0),
            "peak_kv_utilization": max(self.kv_utilization, default=0.0),
            "mean_kv_utilization": (sum(self.kv_utilization)
                                    / len(self.kv_utilization)
                                    if self.kv_utilization else 0.0),
            "mean_ttft": sum(ttfts) / len(ttfts) if ttfts else None,
            "max_ttft": max(ttfts, default=None) if ttfts else None,
            "mean_itl": sum(itls) / len(itls) if itls else None,
            "decode": self.decode_stats(),
            "spec": self.spec_stats(),
            "failures": self.failure_stats(),
            "recovery": self.recovery_stats(),
            "prefix_cache": self.prefix_stats(),
            "compilation": self.compile_stats(),
            "requests": {rid: m.to_dict()
                         for rid, m in self.requests.items()},
        }

    def maybe_dump(self, name: str = "serve_metrics") -> Optional[str]:
        """Write the summary as JSON under the IR-dump dir when
        ``TDT_DUMP_IR`` is set (runtime/dump.py — one observability
        switch for kernels AND serving); no-op otherwise."""
        directory = dump.dump_dir()
        if directory is None:
            return None
        path = os.path.join(directory, dump._safe(name) + ".json")
        dump._write(path, json.dumps(self.summary(), indent=2))
        return path
