"""Serving metrics: per-request latencies + engine-level gauges.

Per request: TTFT (arrival → first emitted token), inter-token latencies,
queue wait (arrival → first scheduled).  Per engine step: queue depth,
running batch occupancy, KV-block utilization; counters for preemptions,
prefill tokens, decode/verify passes.  Compilation observability: the
engine registers its ``jit_cache.CountingJit``-wrapped programs here, so
trace-cache hits/misses, cumulative compile-stall time, warmup coverage,
and the process-wide ``cached_shard_jit`` stats all land in
:meth:`ServeMetrics.summary` under ``"compilation"`` (docs/serving.md
"Reading the compile metrics").

Memory is BOUNDED for long-lived engines (docs/observability.md): the
per-step gauge series are streaming aggregates (last/peak/mean — never
per-step lists), per-request ``token_times`` keeps a fixed recent
window, latency distributions live in log-bucketed
:class:`serve.trace.LogHistogram` fields (TTFT / ITL / queue-time /
step-time / snapshot-time with p50/p95/p99 in ``summary()``), and the
retired-request map prunes past ``requests_retain`` — consistent with
the journal's ``journal_retain_done`` pruning, so neither RSS nor
``summary()`` cost grows with every request or token ever served.

Export rides three paths: ``TDT_DUMP_IR=<dir>`` +
:meth:`ServeMetrics.maybe_dump` writes ``<dir>/<name>.json`` next to the
kernel IR dumps (one switch arms both); :meth:`ServeMetrics.to_prometheus`
is the text exposition behind ``examples/serve.py --metrics-port``
(served by ``serve.trace.start_metrics_server``); and
:func:`format_stats` / :func:`format_statline` are THE human-readable
renderings — the CLI's end-of-run block, its periodic one-liner, and the
supervisor's postmortem line all come from here, so the stats can never
drift between surfaces.
"""

from __future__ import annotations

import json
import os
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from triton_dist_tpu.runtime import dump
from triton_dist_tpu.serve.trace import LogHistogram

#: Recent token timestamps one request retains (the bounded window
#: behind ``inter_token_latencies`` and horizon burst pacing; full
#: distributions live in the engine-level histograms).
TOKEN_TIMES_WINDOW = 256

#: Retired requests ``ServeMetrics.requests`` keeps before pruning the
#: oldest (per-request detail only; the aggregate counters and
#: histograms keep counting forever).  Matches the journal's
#: ``journal_retain_done`` default.
REQUESTS_RETAIN = 4096

#: Counter fields :meth:`ServeMetrics.merge` adds across engines — the
#: fleet aggregation contract (serve/fleet.py): every additive counter
#: in the exposition sums replica-wise, histograms merge bucket-exactly,
#: gauges take last-sum/peak-max.  A counter added to ServeMetrics
#: without joining this tuple silently vanishes from the fleet
#: aggregate, so keep them in lockstep.
MERGE_COUNTERS = (
    "steps", "decode_steps", "verify_rounds", "prefill_tokens",
    "preemptions", "completed", "decode_tokens", "dispatches",
    "host_syncs", "shed", "deadline_expired", "quarantined",
    "callback_errors", "forward_retries", "forward_bisections",
    "watchdog_trips", "spec_bailouts", "spec_rounds", "spec_proposed",
    "spec_accepted", "spec_tokens", "spec_dispatches",
    "draft_prefix_skipped_tokens", "snapshots", "snapshot_ms_total",
    "journal_records", "journal_bytes", "journal_rotations", "restores",
    "restored_in_place", "restored_requeued", "restored_tokens",
    "migrated_out", "migrated_in", "migrated_in_place",
    "migrated_tokens", "pushed_out", "pushed_in",
    "prefix_hits", "prefix_hit_tokens",
    "prefix_skipped_tokens", "running_sum", "kv_util_sum",
    "net_requests", "net_dup_hits", "net_redelivered_tokens",
    "brownout_transitions",
    "journal_corrupt", "manifest_corrupt",
)


class WindowedRate:
    """Bounded sliding-window event counter — the SLO burn-rate
    primitive (docs/observability.md "Fleet observability").

    Cumulative counters answer "how many ever"; an SLO burn alert needs
    "how many in the last W seconds".  ``observe(ts)`` records one
    event; ``count(now)``/``rate(now)`` report the trailing window.
    Memory is bounded two ways: expired timestamps drop on every call,
    and the deque caps at ``max_events`` (saturation flags rather than
    grows — at that point the rate is "a lot", exactly what the alert
    needed to know)."""

    def __init__(self, window_s: float = 60.0, max_events: int = 65536):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = window_s
        self.max_events = max_events
        self._ts = deque(maxlen=max_events)
        self.total = 0

    def observe(self, ts: float, n: int = 1) -> None:
        self.total += n
        for _ in range(n):
            self._ts.append(ts)

    def _trim(self, now: float) -> None:
        lo = now - self.window_s
        while self._ts and self._ts[0] < lo:
            self._ts.popleft()

    def count(self, now: float) -> int:
        """Events inside ``[now - window_s, now]``."""
        self._trim(now)
        return len(self._ts)

    def rate(self, now: float) -> float:
        """Events per second over the trailing window."""
        return self.count(now) / self.window_s

    @property
    def saturated(self) -> bool:
        return len(self._ts) == self.max_events


@dataclass
class RequestMetrics:
    """Timestamps (engine clock) and derived latencies for one request."""

    arrival_time: float
    first_scheduled_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    # recent token timestamps only (bounded: a long stream must not grow
    # host memory); times_dropped counts the forgotten prefix, so
    # n_tokens and index math stay exact
    token_times: list[float] = field(default_factory=list)
    times_dropped: int = 0
    # queue-time histogram guard: first_scheduled_time is first-write-
    # wins, so only the FIRST admission's wait may feed hist_queue (a
    # preempted request's re-admissions would re-observe the same value)
    queue_observed: bool = False
    n_preemptions: int = 0
    # prefix cache (docs/serving.md "Prefix caching"): prompt tokens
    # covered by shared cached blocks at this request's admission — a
    # warm request skips that much prefill compute, so its TTFT is the
    # number the cache exists to collapse
    cached_prefix_tokens: int = 0

    def on_scheduled(self, now: float) -> None:
        if self.first_scheduled_time is None:
            self.first_scheduled_time = now

    def on_token(self, now: float) -> Optional[float]:
        """Record one emission; returns the inter-token latency this
        token closes (``None`` for the first token) so the engine can
        feed the ITL histogram without re-deriving it."""
        itl = (now - self.token_times[-1]) if self.token_times else None
        if self.first_token_time is None:
            self.first_token_time = now
            itl = None
        self.token_times.append(now)
        extra = len(self.token_times) - TOKEN_TIMES_WINDOW
        if extra > 0:
            del self.token_times[:extra]
            self.times_dropped += extra
        return itl

    @property
    def n_tokens(self) -> int:
        return self.times_dropped + len(self.token_times)

    def seed_token_times(self, times: list, total: Optional[int] = None
                         ) -> None:
        """Restore-time seeding (serve/recovery.py): install journal/
        manifest timestamps under the same bounded-window invariants
        ``on_token`` maintains.  ``total`` is the true emission count
        when timestamps were lost (rotation/window pruning writes
        ``None`` pads) so ``n_tokens`` stays exact."""
        times = [t for t in times if t is not None]
        extra = len(times) - TOKEN_TIMES_WINDOW
        if extra > 0:
            del times[:extra]
        self.token_times = times
        n = total if total is not None else len(times)
        self.times_dropped = max(0, n - len(times))
        if times and self.first_token_time is None:
            self.first_token_time = times[0]

    def time_at(self, i: int) -> Optional[float]:
        """Timestamp of emission index ``i``, or ``None`` once the
        bounded window has dropped it (journal backfill/rotation use
        this instead of indexing the raw list — the window's base
        shifts)."""
        j = i - self.times_dropped
        if 0 <= j < len(self.token_times):
            return self.token_times[j]
        return None

    def burst_times(self, now: float, n: int, step_s: float) -> list[float]:
        """Timestamps for ``n`` tokens committed in ONE decode-horizon
        drain: spaced backwards from ``now`` by the DEVICE step cadence
        (``step_s`` = horizon wall time / device steps) instead of
        collapsing onto the drain instant.  Burst commits would otherwise
        read as ITL 0 inside a burst and a full horizon between bursts —
        the per-token latency a client streaming from the engine actually
        sees is the device's, and this reconstructs it."""
        return [now - i * step_s for i in range(n - 1, -1, -1)]

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token (arrival → first emission)."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    @property
    def queue_time(self) -> Optional[float]:
        if self.first_scheduled_time is None:
            return None
        return self.first_scheduled_time - self.arrival_time

    @property
    def inter_token_latencies(self) -> list[float]:
        """Gaps within the RECENT window (full distributions live in the
        engine-level ITL histogram)."""
        t = self.token_times
        return [b - a for a, b in zip(t, t[1:])]

    @property
    def mean_itl(self) -> Optional[float]:
        itl = self.inter_token_latencies
        return sum(itl) / len(itl) if itl else None

    def to_dict(self) -> dict:
        return {
            "arrival_time": self.arrival_time,
            "ttft": self.ttft,
            "queue_time": self.queue_time,
            "mean_itl": self.mean_itl,
            "n_tokens": self.n_tokens,
            "n_preemptions": self.n_preemptions,
            "cached_prefix_tokens": self.cached_prefix_tokens,
            "finish_time": self.finish_time,
        }


@dataclass
class ServeMetrics:
    """Engine-level counters + streaming per-step gauges."""

    # counters
    steps: int = 0
    decode_steps: int = 0
    verify_rounds: int = 0
    prefill_tokens: int = 0
    preemptions: int = 0
    completed: int = 0
    # decode-loop dispatch accounting (docs/serving.md "Decode horizon"):
    # how many device dispatches and host sync points the decode path
    # paid per emitted token.  At horizon H=1 every token costs one
    # dispatch + one sync; the fused horizon amortizes both — the
    # dispatches_per_token quotient is THE metric the horizon exists to
    # shrink.
    decode_tokens: int = 0        # tokens committed by the decode loop
    dispatches: int = 0           # decode-path device dispatches
    host_syncs: int = 0           # decode-path host sync points
    # failure-containment counters (docs/serving.md "Failure
    # containment"): every non-healthy retirement and every recovery
    # action is a counter, so overload and poison traffic are visible
    # in the same summary as latency.
    shed: int = 0                 # submit() rejections (queue at bound)
    deadline_expired: int = 0     # WAITING/PREFILL TTL sweeps
    quarantined: int = 0          # requests retired FinishReason.ERROR
    callback_errors: int = 0      # on_token raised; callback disabled
    forward_retries: int = 0      # batched-forward retry attempts
    forward_bisections: int = 0   # batch splits isolating a poison row
    watchdog_trips: int = 0       # step watchdog timeouts (re-raised)
    spec_bailouts: int = 0        # speculative rounds latched off
    # speculative-decoding counters (docs/serving.md "Speculative
    # decoding"): acceptance is the number that decides whether
    # speculation pays — proposed/accepted feed the overall and rolling
    # rates, chosen_k histograms the adaptive per-row depth, and
    # spec_tokens/spec_dispatches give tokens-per-dispatch for the fused
    # round alone (the ISSUE-7 guardrail: >= plain fused decode).
    spec_rounds: int = 0          # fused rounds that emitted something
    spec_proposed: int = 0        # draft tokens proposed (per-row budget)
    spec_accepted: int = 0        # proposals the target's stream matched
    spec_tokens: int = 0          # tokens committed by spec rounds
    spec_dispatches: int = 0      # fused spec-round dispatches
    spec_recent: list = field(default_factory=list, repr=False)
    spec_chosen_k: dict = field(default_factory=dict)
    draft_prefix_skipped_tokens: int = 0  # draft prefill skipped via the
    #                               draft-side page cache (warm admits)
    # retirements by FinishReason.value
    finish_reasons: dict = field(default_factory=dict)
    # per-SLO-class accounting (docs/serving.md "Overload, SLO classes
    # & autoscaling"): every counter keyed by slo_class so overload
    # response is auditable PER TIER — "best_effort shed, interactive
    # untouched" must be a number, not a claim.  Labeled dicts merge
    # by-key across the fleet (the finish_reasons pattern), per-class
    # TTFT histograms merge bucket-exactly by class (the program_hists
    # pattern).  All-default traffic lands every count under
    # "interactive", so the split costs nothing to read.
    class_submitted: dict = field(default_factory=dict)
    class_finished: dict = field(default_factory=dict)
    class_shed: dict = field(default_factory=dict)
    class_deadline: dict = field(default_factory=dict)
    class_preempted: dict = field(default_factory=dict)
    class_ttft: dict = field(default_factory=dict, repr=False)
    # graceful-degradation ladder (engine brownout): the rung the
    # engine currently sits on (0 = full service), its lifetime peak,
    # and how many rung transitions it has walked.  Rung gauges take
    # max across the fleet ("the worst brownout anywhere" is the
    # alertable fact); transitions is an additive MERGE_COUNTERS
    # member.
    brownout_rung_last: int = 0
    brownout_rung_peak: int = 0
    brownout_transitions: int = 0
    # crash-recovery counters (docs/serving.md "Crash recovery"):
    # snapshot latency + journal overhead on the serving side, restore
    # provenance on the resume side (how much state came back in place
    # vs through exact recompute).
    snapshots: int = 0            # engine.snapshot() captures
    snapshot_ms_last: float = 0.0
    snapshot_ms_total: float = 0.0
    journal_records: int = 0      # journal appends by this engine
    journal_bytes: int = 0
    journal_rotations: int = 0    # compactions at snapshot barriers
    restores: int = 0             # 1 on an engine built by restore()
    restored_in_place: int = 0    # requests resumed with live KV
    restored_requeued: int = 0    # requests re-queued for recompute
    restored_tokens: int = 0      # journal tokens carried across
    # live-migration counters (docs/serving.md "Fleet serving"): the
    # hand-off twins of the restore provenance fields — how many
    # requests left this engine mid-stream (drain) and how many arrived
    # (migrate_in, split by in-place KV adopt vs exact-recompute
    # requeue), plus the journal tokens that crossed with them.
    migrated_out: int = 0         # requests drained to a manifest
    migrated_in: int = 0          # manifest requests this engine adopted
    migrated_in_place: int = 0    # adopted WITH live KV (no recompute)
    migrated_tokens: int = 0      # journal tokens carried by migrations
    # disaggregated prefill->decode counters (serve/disagg.py,
    # docs/serving.md "Disaggregated serving"): per-request KV-page
    # PUSH hand-offs at prefill completion — distinct from the
    # migration counters above so tier hand-offs and failure-driven
    # moves stay separately alertable.
    pushed_out: int = 0           # requests pushed to a decode replica
    pushed_in: int = 0            # pushed requests this engine admitted
    # prefix-cache counters (docs/serving.md "Prefix caching"): engine-
    # side admission hits; the block-level gauges (refcounts, cache
    # tier, COW/eviction counts) live on the attached BlockManager and
    # merge into summary()["prefix_cache"] via attach_block_manager().
    prefix_hits: int = 0          # admissions mapping >= 1 shared block
    prefix_hit_tokens: int = 0    # prompt tokens covered by shared blocks
    prefix_skipped_tokens: int = 0  # prefill tokens actually skipped
    # network serving plane counters (serve/net.py, docs/serving.md
    # "Network fleet serving"): how often the wire asked, how often
    # idempotency made a retried call a no-op (duplicate submit, cached
    # drain/migrate replay), and how many tokens were SERVED again
    # because a stream poll re-read indices below the high-water mark
    # (an ack lost to the network re-delivers but never re-derives).
    net_requests: int = 0         # API calls the replica server answered
    net_dup_hits: int = 0         # idempotent no-op replays
    net_redelivered_tokens: int = 0  # tokens re-served below the watermark
    # state-integrity counters (serve/integrity.py, docs/serving.md
    # "Durability & integrity"): journal_corrupt counts salvage events
    # (interior damage quarantined, longest-valid prefix replayed);
    # manifest_corrupt counts wire manifests a RECEIVER rejected on a
    # digest mismatch (the sender re-queues through exact recompute —
    # corruption is never adopted, so either counter being nonzero is
    # an alert about the storage/transport substrate, not about
    # correctness).
    journal_corrupt: int = 0      # journal salvage (quarantine) events
    manifest_corrupt: int = 0     # wire manifests rejected on digest
    block_manager: object = field(default=None, repr=False)
    # compilation observability: CountingJit wrappers the engine
    # registers (runtime/jit_cache.py) + warmup accounting
    compiled_fns: list = field(default_factory=list, repr=False)
    warmup_time: float = 0.0
    warmup_compiles: int = 0
    # per-step gauges as STREAMING aggregates (last / peak / running
    # sums) — never per-step lists, so a long-lived engine's metrics
    # stay O(1) regardless of how many steps it has served
    queue_depth_last: int = 0
    queue_depth_peak: int = 0
    running_last: int = 0
    running_sum: int = 0
    kv_util_last: float = 0.0
    kv_util_peak: float = 0.0
    kv_util_sum: float = 0.0
    # KV pool capacity gauges (docs/serving.md "Quantized serving"):
    # stamped once at construction by set_kv_capacity() — the resident
    # bytes the paged pools pin on device and the token slots they buy.
    # bytes/token is THE quotient int8 pools exist to shrink (scales
    # included: int8 pays Hkv*(D+4) per token-layer-plane vs fp32's
    # Hkv*D*4), and the capacity bench gates its ratio across dtypes.
    kv_pool_bytes: int = 0        # device bytes pinned by the KV pools
    kv_token_slots: int = 0       # num_blocks * page_size token capacity
    kv_quant: bool = False        # pools hold int8 pages + f32 scales
    # SLO latency histograms (serve/trace.LogHistogram): log-bucketed,
    # bounded, p50/p95/p99 in summary()["latency"] and the Prometheus
    # exposition.  TTFT/ITL/queue on the ENGINE clock; step/snapshot on
    # wall time (the engine clock may be fake under chaos tests).
    hist_ttft: LogHistogram = field(default_factory=LogHistogram,
                                    repr=False)
    hist_itl: LogHistogram = field(default_factory=LogHistogram,
                                   repr=False)
    hist_queue: LogHistogram = field(default_factory=LogHistogram,
                                     repr=False)
    hist_step: LogHistogram = field(default_factory=LogHistogram,
                                    repr=False)
    hist_snapshot: LogHistogram = field(default_factory=LogHistogram,
                                        repr=False)
    # per-program wall-time attribution (docs/observability.md "Kernel
    # observability"): one LogHistogram of per-call wall MILLISECONDS
    # per device program (paged_decode, decode_horizon[H=8], prefill
    # chunk, verify, spec rung, page scatter/gather/COW), fed by the
    # CountingJit/ShardedProgram ``timer`` hook the engine wires when
    # trace_level >= 1 — engine step time decomposes by program instead
    # of being one opaque hist_step.  ``program_timing`` is the master
    # gate (warmup pauses it so compile stalls never pollute p99).
    program_hists: dict = field(default_factory=dict, repr=False)
    program_timing: bool = False
    # flight recorder (serve/trace.FlightRecorder) the engine attaches
    # so the exposition can report ring occupancy
    recorder: object = field(default=None, repr=False)
    # retired requests' metrics, keyed by request id; pruned oldest-first
    # past requests_retain (None keeps everything — unit-test mode)
    requests: dict = field(default_factory=dict)
    requests_retain: Optional[int] = REQUESTS_RETAIN

    def observe_step(self, *, queue_depth: int, running: int,
                     kv_utilization: float) -> None:
        self.steps += 1
        self.queue_depth_last = queue_depth
        if queue_depth > self.queue_depth_peak:
            self.queue_depth_peak = queue_depth
        self.running_last = running
        self.running_sum += running
        self.kv_util_last = kv_utilization
        self.kv_util_sum += kv_utilization
        if kv_utilization > self.kv_util_peak:
            self.kv_util_peak = kv_utilization

    # -- KV pool capacity --------------------------------------------------

    def set_kv_capacity(self, *, pool_bytes: int, token_slots: int,
                        quantized: bool) -> None:
        """Stamp the engine's KV pool geometry (the engine calls this at
        construction, right after allocating pools): resident device
        bytes across every pool leaf (int8 pages AND their f32 scales
        both count — the scales are real memory), the token slots those
        bytes buy (``num_blocks * page_size``), and whether the pools
        are quantized.  Feeds ``summary()["kv"]``, the
        ``serve_kv_pool_bytes`` / ``serve_kv_bytes_per_token`` gauges,
        and the CLI stats block."""
        self.kv_pool_bytes = int(pool_bytes)
        self.kv_token_slots = int(token_slots)
        self.kv_quant = bool(quantized)

    def kv_stats(self) -> dict:
        """KV pool capacity (summary()["kv"]): pool bytes, token slots,
        and bytes/token — the memory-economics view the int8 pools
        exist to move (docs/serving.md "Quantized serving")."""
        return {
            "pool_bytes": self.kv_pool_bytes,
            "token_slots": self.kv_token_slots,
            "bytes_per_token": (self.kv_pool_bytes / self.kv_token_slots
                                if self.kv_token_slots else 0.0),
            "quantized": self.kv_quant,
        }

    # -- per-program wall-time attribution --------------------------------

    def program_hist(self, name: str) -> LogHistogram:
        """Get-or-create the per-call wall-time histogram (milliseconds)
        for device program ``name`` — every engine shares one bucket
        scheme so :meth:`merge` and ``merge_scrapes`` stay bucket-exact
        across the fleet."""
        h = self.program_hists.get(name)
        if h is None:
            h = self.program_hists[name] = LogHistogram()
        return h

    def observe_program(self, name: str, ms: float) -> None:
        """One program call's wall time (the CountingJit/ShardedProgram
        ``timer`` hook target).  No-op while ``program_timing`` is off —
        the trace_level gate and warmup's pause both flip this flag, so
        the hot path stays one attribute check when attribution is
        disabled and compile stalls never land in the distributions."""
        if not self.program_timing:
            return
        self.program_hist(name).observe(ms)

    def program_stats(self) -> dict:
        """``summary()["programs"]``: per-program p50/p95/p99/mean/count
        wall milliseconds — which device program ate a slow step, as a
        number instead of archaeology."""
        return {name: self.program_hists[name].stats()
                for name in sorted(self.program_hists)}

    def observe_finish(self, request_id: str, rm: RequestMetrics,
                       reason=None, slo_class: str = "interactive"
                       ) -> None:
        self.completed += 1
        self.requests[request_id] = rm
        if self.requests_retain is not None:
            # dict preserves insertion order: drop the oldest retirement
            # (O(overflow) per finish — never materialize the whole map)
            while len(self.requests) > self.requests_retain:
                del self.requests[next(iter(self.requests))]
        self._bump(self.class_finished, slo_class)
        if reason is not None:
            key = getattr(reason, "value", str(reason))
            self.finish_reasons[key] = self.finish_reasons.get(key, 0) + 1
            if key == "shed":
                self._bump(self.class_shed, slo_class)
            elif key == "deadline":
                self._bump(self.class_deadline, slo_class)

    # -- per-SLO-class accounting ------------------------------------------

    @staticmethod
    def _bump(d: dict, key: str, n: int = 1) -> None:
        d[key] = d.get(key, 0) + n

    def observe_class_submit(self, slo_class: str) -> None:
        """One request accepted into the engine queue, by class."""
        self._bump(self.class_submitted, slo_class)

    def observe_class_preempt(self, slo_class: str) -> None:
        """One preemption eviction, by the victim's class — with the
        class-aware scheduler on, this is the proof best-effort absorbs
        the pressure before interactive does."""
        self._bump(self.class_preempted, slo_class)

    def class_ttft_hist(self, slo_class: str) -> LogHistogram:
        """Get-or-create the per-class TTFT histogram — one bucket
        scheme across classes and engines, so fleet merge stays
        bucket-exact (the ``program_hists`` pattern)."""
        h = self.class_ttft.get(slo_class)
        if h is None:
            h = self.class_ttft[slo_class] = LogHistogram()
        return h

    def observe_brownout(self, rung: int) -> None:
        """One brownout-ladder transition (engine `_brownout_step`):
        the new rung becomes the gauge, every transition counts."""
        self.brownout_transitions += 1
        self.brownout_rung_last = rung
        if rung > self.brownout_rung_peak:
            self.brownout_rung_peak = rung

    def slo_stats(self) -> dict:
        """Per-class overload accounting (summary()["slo"]): submitted/
        finished/shed/deadline/preempted by class, per-class TTFT
        percentiles, and the brownout rung — the per-tier view the SLO
        classes exist to provide."""
        return {
            "submitted": dict(sorted(self.class_submitted.items())),
            "finished": dict(sorted(self.class_finished.items())),
            "shed": dict(sorted(self.class_shed.items())),
            "deadline_expired": dict(sorted(self.class_deadline.items())),
            "preempted": dict(sorted(self.class_preempted.items())),
            "ttft": {c: self.class_ttft[c].stats()
                     for c in sorted(self.class_ttft)},
            "brownout_rung": self.brownout_rung_last,
            "brownout_rung_peak": self.brownout_rung_peak,
            "brownout_transitions": self.brownout_transitions,
        }

    def failure_stats(self) -> dict:
        """The containment counters as one dict (summary()["failures"])."""
        return {
            "shed": self.shed,
            "deadline_expired": self.deadline_expired,
            "quarantined": self.quarantined,
            "callback_errors": self.callback_errors,
            "forward_retries": self.forward_retries,
            "forward_bisections": self.forward_bisections,
            "watchdog_trips": self.watchdog_trips,
            "spec_bailouts": self.spec_bailouts,
            "finish_reasons": dict(self.finish_reasons),
        }

    def observe_spec_row(self, proposed: int, accepted: int,
                         chosen_k: int) -> None:
        """One row's share of one fused speculative round (the engine
        calls this at each round's drain)."""
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.spec_recent.append((proposed, accepted))
        del self.spec_recent[:-64]
        self.spec_chosen_k[chosen_k] = \
            self.spec_chosen_k.get(chosen_k, 0) + 1

    def spec_stats(self) -> dict:
        """Speculative-decoding observability (summary()["spec"]):
        per-round proposed/accepted counters, the overall and ROLLING
        (last 64 row-rounds) acceptance rates, the chosen-k histogram
        the adaptive policy produced, and spec tokens-per-dispatch —
        the economics field the fused round exists to move."""
        rp = sum(p for p, _ in self.spec_recent)
        ra = sum(a for _, a in self.spec_recent)
        return {
            "rounds": self.spec_rounds,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "accept_rate": (self.spec_accepted / self.spec_proposed
                            if self.spec_proposed else 0.0),
            "rolling_accept_rate": (ra / rp if rp else 0.0),
            "chosen_k": dict(sorted(self.spec_chosen_k.items())),
            "spec_tokens": self.spec_tokens,
            "spec_dispatches": self.spec_dispatches,
            "spec_tokens_per_dispatch": (
                self.spec_tokens / self.spec_dispatches
                if self.spec_dispatches else 0.0),
            "bailouts": self.spec_bailouts,
            "draft_prefix_skipped_tokens": self.draft_prefix_skipped_tokens,
        }

    def recovery_stats(self) -> dict:
        """Snapshot/journal/restore counters (summary()["recovery"])."""
        return {
            "snapshots": self.snapshots,
            "snapshot_ms_last": self.snapshot_ms_last,
            "snapshot_ms_total": self.snapshot_ms_total,
            "journal_records": self.journal_records,
            "journal_bytes": self.journal_bytes,
            "journal_rotations": self.journal_rotations,
            "restores": self.restores,
            "restored_in_place": self.restored_in_place,
            "restored_requeued": self.restored_requeued,
            "restored_tokens": self.restored_tokens,
            "journal_corrupt": self.journal_corrupt,
        }

    def migration_stats(self) -> dict:
        """Live-migration provenance (summary()["migration"]) — the
        fleet hand-off counters (docs/serving.md "Fleet serving")."""
        return {
            "migrated_out": self.migrated_out,
            "migrated_in": self.migrated_in,
            "migrated_in_place": self.migrated_in_place,
            "migrated_tokens": self.migrated_tokens,
            "pushed_out": self.pushed_out,
            "pushed_in": self.pushed_in,
        }

    def net_stats(self) -> dict:
        """Network serving plane counters (summary()["net"]) — the wire
        side of docs/serving.md "Network fleet serving"."""
        return {
            "net_requests": self.net_requests,
            "net_dup_hits": self.net_dup_hits,
            "net_redelivered_tokens": self.net_redelivered_tokens,
            "manifest_corrupt": self.manifest_corrupt,
        }

    def merge(self, other: "ServeMetrics") -> "ServeMetrics":
        """Fold another engine's metrics into this one — the fleet
        aggregation primitive (serve/fleet.py,
        ``FleetController.aggregate_metrics``).  Counters add
        (:data:`MERGE_COUNTERS` — the exposition's additive series),
        the SLO histograms merge bucket-EXACTLY
        (:meth:`serve.trace.LogHistogram.merge`: identical schemes add
        count-wise, so fleet p50/p95/p99 equal percentiles over the
        pooled per-replica samples), finish-reason tallies add, and
        gauges take sum-of-last / max-of-peak.  Per-request detail
        (``requests``), compiled-program registries, and recorder
        attachments stay local — they name objects, not quantities."""
        for name in MERGE_COUNTERS:
            setattr(self, name, getattr(self, name)
                    + getattr(other, name))
        self.snapshot_ms_last = max(self.snapshot_ms_last,
                                    other.snapshot_ms_last)
        self.queue_depth_last += other.queue_depth_last
        self.queue_depth_peak = max(self.queue_depth_peak,
                                    other.queue_depth_peak)
        self.running_last += other.running_last
        self.kv_util_last = max(self.kv_util_last, other.kv_util_last)
        self.kv_util_peak = max(self.kv_util_peak, other.kv_util_peak)
        # KV capacity sums replica-wise (the fleet's pooled bytes and
        # slots; bytes/token re-derives from the sums, so a mixed
        # int8/fp fleet reports its true blended quotient); kv_quant
        # ORs — "any replica quantized" is the alertable fact
        self.kv_pool_bytes += other.kv_pool_bytes
        self.kv_token_slots += other.kv_token_slots
        self.kv_quant = self.kv_quant or other.kv_quant
        for reason, n in other.finish_reasons.items():
            self.finish_reasons[reason] = \
                self.finish_reasons.get(reason, 0) + n
        # per-class tallies merge by key (the finish_reasons pattern);
        # brownout rung gauges take max — "the worst rung anywhere"
        for mine, theirs in (
                (self.class_submitted, other.class_submitted),
                (self.class_finished, other.class_finished),
                (self.class_shed, other.class_shed),
                (self.class_deadline, other.class_deadline),
                (self.class_preempted, other.class_preempted)):
            for cls, n in theirs.items():
                mine[cls] = mine.get(cls, 0) + n
        for cls, theirs in other.class_ttft.items():
            self.class_ttft_hist(cls).merge(theirs)
        self.brownout_rung_last = max(self.brownout_rung_last,
                                      other.brownout_rung_last)
        self.brownout_rung_peak = max(self.brownout_rung_peak,
                                      other.brownout_rung_peak)
        for mine, theirs in ((self.hist_ttft, other.hist_ttft),
                             (self.hist_itl, other.hist_itl),
                             (self.hist_queue, other.hist_queue),
                             (self.hist_step, other.hist_step),
                             (self.hist_snapshot, other.hist_snapshot)):
            mine.merge(theirs)
        # per-program wall-time histograms merge bucket-exactly by name
        # (a program only one replica ran still joins the aggregate)
        for name, theirs in other.program_hists.items():
            self.program_hist(name).merge(theirs)
        return self

    def attach_block_manager(self, bm) -> None:
        """Fold the block manager's prefix-cache gauges into
        :meth:`summary` (the engine calls this at construction)."""
        self.block_manager = bm

    def attach_recorder(self, recorder) -> None:
        """Track the engine's flight recorder so the exposition reports
        ring occupancy/drops alongside the counters."""
        self.recorder = recorder

    def prefix_stats(self) -> dict:
        """Admission-level hit counters + block-level cache gauges +
        the warm/cold TTFT split (summary()["prefix_cache"]).  A warm
        request is one whose admission mapped >= 1 shared block;
        ``ttft_warm_over_cold`` is the ratio the cache exists to
        collapse (the bench gate holds it <= 0.35 for a shared-prompt
        workload)."""
        warm = [m.ttft for m in self.requests.values()
                if m.cached_prefix_tokens > 0 and m.ttft is not None]
        cold = [m.ttft for m in self.requests.values()
                if m.cached_prefix_tokens == 0 and m.ttft is not None]
        out = {
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_skipped_tokens": self.prefix_skipped_tokens,
            "warm_requests": len(warm),
            "cold_requests": len(cold),
            "mean_ttft_warm": sum(warm) / len(warm) if warm else None,
            "mean_ttft_cold": sum(cold) / len(cold) if cold else None,
            "ttft_warm_over_cold": (
                (sum(warm) / len(warm)) / (sum(cold) / len(cold))
                if warm and cold and sum(cold) > 0 else None),
        }
        if self.block_manager is not None:
            out.update(self.block_manager.prefix_stats())
        return out

    def decode_stats(self) -> dict:
        """The decode-loop dispatch economics (summary()["decode"]).
        ``dispatches_per_token`` is ~1/batch for per-token decode (one
        dispatch per STEP emits a token per active row) and ~1/(batch·H)
        on a steady fused-horizon batch — the horizon amortizes steps,
        the batch amortizes rows, and only the former is the decode
        horizon's doing; ``host_syncs`` counts the blocking device→host
        fetches the loop paid."""
        return {
            "decode_steps": self.decode_steps,
            "decode_tokens": self.decode_tokens,
            "dispatches": self.dispatches,
            "host_syncs": self.host_syncs,
            "tokens_per_dispatch": (self.decode_tokens / self.dispatches
                                    if self.dispatches else 0.0),
            "dispatches_per_token": (self.dispatches / self.decode_tokens
                                     if self.decode_tokens else 0.0),
        }

    def latency_stats(self) -> dict:
        """The SLO histograms' percentile view (summary()["latency"]):
        p50/p95/p99 + mean + count for TTFT, ITL, queue wait, step wall
        time, and snapshot capture time — the bounded replacement for
        per-request latency lists (docs/observability.md)."""
        return {
            "ttft": self.hist_ttft.stats(),
            "itl": self.hist_itl.stats(),
            "queue": self.hist_queue.stats(),
            "step": self.hist_step.stats(),
            "snapshot": self.hist_snapshot.stats(),
        }

    def light_summary(self) -> dict:
        """Just the fields :func:`format_statline` reads — O(1) scalars
        and histogram scans, never the per-request map that the full
        :meth:`summary` materializes (up to ``requests_retain`` dicts).
        The ``--stats-every`` periodic line and every ``flight_flush``
        use this, so per-step logging and the quarantine path stay
        cheap."""
        return {
            "steps": self.steps,
            "completed": self.completed,
            "max_queue_depth": self.queue_depth_peak,
            "peak_kv_utilization": self.kv_util_peak,
            "decode": self.decode_stats(),
            "latency": self.latency_stats(),
            "programs": self.program_stats(),
        }

    # -- compilation observability ---------------------------------------

    def register_compiled(self, counter) -> None:
        """Track a ``jit_cache.CountingJit``-wrapped program; its
        hit/miss/compile-time counters appear in :meth:`summary` under
        ``compilation`` (and on the ``TDT_DUMP_IR`` dump path).  With
        ``program_timing`` armed the wrapper's ``timer`` hook is wired
        here too, so every registered program feeds its per-call wall
        time into :meth:`observe_program` (docs/observability.md
        "Kernel observability")."""
        self.compiled_fns.append(counter)
        if (self.program_timing
                and getattr(counter, "timer", None) is None):
            counter.timer = self.observe_program

    @property
    def compile_misses(self) -> int:
        """Total trace-cache misses (compiles) across engine programs —
        the bounded-compilation tests watch this stay flat after
        ``engine.warmup()``."""
        return sum(c.misses for c in self.compiled_fns)

    def compile_stats(self) -> dict:
        """Per-program trace-cache counters + the process-wide
        ``cached_shard_jit`` memo stats (runtime/jit_cache.py)."""
        from triton_dist_tpu.runtime import jit_cache

        return {
            "programs": {c.name: c.stats() for c in self.compiled_fns},
            "total_misses": self.compile_misses,
            "total_hits": sum(c.hits for c in self.compiled_fns),
            "total_compile_time_s": sum(c.compile_time
                                        for c in self.compiled_fns),
            "warmup_time_s": self.warmup_time,
            "warmup_compiles": self.warmup_compiles,
            "cached_shard_jit": jit_cache.cache_stats(),
        }

    def summary(self) -> dict:
        """Aggregate view (what the CLI prints and maybe_dump writes)."""
        # TTFT/ITL means from the engine-level histograms (exact
        # sum/count over EVERY request ever served — the requests map
        # prunes past requests_retain, so deriving from it would
        # silently turn into a recent-window mean on long-lived
        # engines); the per-request fallbacks serve metrics objects fed
        # outside an engine (unit tests, hand-built summaries).
        if self.hist_ttft.count:
            mean_ttft = self.hist_ttft.mean
            max_ttft = self.hist_ttft.max
        else:
            ttfts = [m.ttft for m in self.requests.values()
                     if m.ttft is not None]
            mean_ttft = sum(ttfts) / len(ttfts) if ttfts else None
            max_ttft = max(ttfts, default=None) if ttfts else None
        if self.hist_itl.count:
            mean_itl = self.hist_itl.mean
        else:
            itls = [x for m in self.requests.values()
                    for x in m.inter_token_latencies]
            mean_itl = sum(itls) / len(itls) if itls else None
        return {
            "steps": self.steps,
            "decode_steps": self.decode_steps,
            "verify_rounds": self.verify_rounds,
            "prefill_tokens": self.prefill_tokens,
            "preemptions": self.preemptions,
            "completed": self.completed,
            "max_queue_depth": self.queue_depth_peak,
            "mean_running": (self.running_sum / self.steps
                             if self.steps else 0.0),
            "peak_kv_utilization": self.kv_util_peak,
            "mean_kv_utilization": (self.kv_util_sum / self.steps
                                    if self.steps else 0.0),
            "mean_ttft": mean_ttft,
            "max_ttft": max_ttft,
            "mean_itl": mean_itl,
            "latency": self.latency_stats(),
            "programs": self.program_stats(),
            "decode": self.decode_stats(),
            "kv": self.kv_stats(),
            "spec": self.spec_stats(),
            "slo": self.slo_stats(),
            "failures": self.failure_stats(),
            "recovery": self.recovery_stats(),
            "migration": self.migration_stats(),
            "net": self.net_stats(),
            "prefix_cache": self.prefix_stats(),
            "compilation": self.compile_stats(),
            "requests": {rid: m.to_dict()
                         for rid, m in self.requests.items()},
        }

    # -- Prometheus text exposition ---------------------------------------

    def to_prometheus(self) -> str:
        """The engine's live state in the Prometheus text format
        (version 0.0.4) — served by ``serve.trace.start_metrics_server``
        behind ``examples/serve.py --metrics-port``.  Metric names are
        documented in docs/observability.md; counters end ``_total``,
        histograms expose cumulative ``_bucket{le=}`` + ``_sum`` +
        ``_count``."""
        L: list[str] = []

        def counter(name, v, help_=None):
            if help_:
                L.append(f"# HELP {name} {help_}")
            L.append(f"# TYPE {name} counter")
            L.append(f"{name} {v}")

        def gauge(name, v, help_=None):
            if help_:
                L.append(f"# HELP {name} {help_}")
            L.append(f"# TYPE {name} gauge")
            L.append(f"{name} {v}")

        counter("serve_steps_total", self.steps,
                "engine scheduler iterations")
        counter("serve_decode_steps_total", self.decode_steps)
        counter("serve_decode_tokens_total", self.decode_tokens)
        counter("serve_prefill_tokens_total", self.prefill_tokens)
        counter("serve_dispatches_total", self.dispatches,
                "decode-path device dispatches")
        counter("serve_host_syncs_total", self.host_syncs)
        counter("serve_completed_total", self.completed,
                "requests retired (any reason)")
        counter("serve_preemptions_total", self.preemptions)
        counter("serve_shed_total", self.shed)
        counter("serve_deadline_expired_total", self.deadline_expired)
        counter("serve_quarantined_total", self.quarantined)
        counter("serve_callback_errors_total", self.callback_errors)
        counter("serve_forward_retries_total", self.forward_retries)
        counter("serve_forward_bisections_total", self.forward_bisections)
        counter("serve_watchdog_trips_total", self.watchdog_trips)
        counter("serve_spec_bailouts_total", self.spec_bailouts)
        counter("serve_spec_proposed_total", self.spec_proposed)
        counter("serve_spec_accepted_total", self.spec_accepted)
        counter("serve_snapshots_total", self.snapshots)
        counter("serve_journal_records_total", self.journal_records)
        counter("serve_journal_rotations_total", self.journal_rotations)
        counter("serve_migrated_out_total", self.migrated_out,
                "requests drained to a migration manifest")
        counter("serve_migrated_in_total", self.migrated_in,
                "manifest requests adopted from another replica")
        counter("serve_pushed_out_total", self.pushed_out,
                "requests pushed to a decode replica at prefill end")
        counter("serve_pushed_in_total", self.pushed_in,
                "pushed requests admitted from a prefill replica")
        counter("serve_prefix_hits_total", self.prefix_hits)
        counter("serve_prefix_skipped_tokens_total",
                self.prefix_skipped_tokens)
        counter("serve_net_requests_total", self.net_requests,
                "network serving-plane API calls answered")
        counter("serve_net_dup_hits_total", self.net_dup_hits,
                "idempotent no-op replays (duplicate submit, cached "
                "drain/migrate response)")
        counter("serve_net_redelivered_tokens_total",
                self.net_redelivered_tokens,
                "tokens re-served below a stream's high-water mark")
        counter("serve_journal_corrupt_total", self.journal_corrupt,
                "journal salvage events (interior corruption "
                "quarantined, longest-valid prefix replayed)")
        counter("serve_manifest_corrupt_total", self.manifest_corrupt,
                "wire manifests rejected on a digest mismatch "
                "(sender re-queues through exact recompute)")
        L.append("# TYPE serve_finished_total counter")
        for reason, n in sorted(self.finish_reasons.items()):
            L.append(f'serve_finished_total{{reason="{reason}"}} {n}')
        # per-SLO-class series: labeled counter families (one TYPE
        # header each) + the per-class TTFT histogram family
        for name, d in (("serve_class_submitted_total",
                         self.class_submitted),
                        ("serve_class_finished_total",
                         self.class_finished),
                        ("serve_class_shed_total", self.class_shed),
                        ("serve_class_deadline_expired_total",
                         self.class_deadline),
                        ("serve_class_preempted_total",
                         self.class_preempted)):
            L.append(f"# TYPE {name} counter")
            for cls, n in sorted(d.items()):
                L.append(f'{name}{{slo_class="{cls}"}} {n}')
        for i, cls in enumerate(sorted(self.class_ttft)):
            L.extend(self.class_ttft[cls].prom_lines(
                "serve_class_ttft_seconds", labels=f'slo_class="{cls}"',
                typed=i == 0))
        counter("serve_brownout_transitions_total",
                self.brownout_transitions,
                "graceful-degradation ladder rung transitions")
        gauge("serve_brownout_rung", self.brownout_rung_last,
              "current brownout rung (0 = full service)")
        gauge("serve_queue_depth", self.queue_depth_last,
              "waiting requests at the last engine step")
        gauge("serve_running", self.running_last)
        gauge("serve_kv_utilization", round(self.kv_util_last, 6))
        gauge("serve_kv_pool_bytes", self.kv_pool_bytes,
              "device bytes pinned by the paged KV pools "
              "(int8 pages + f32 scales both count)")
        gauge("serve_kv_token_slots", self.kv_token_slots,
              "token capacity of the pools (num_blocks * page_size)")
        gauge("serve_kv_bytes_per_token",
              round(self.kv_pool_bytes / self.kv_token_slots, 6)
              if self.kv_token_slots else 0.0,
              "KV pool bytes per token slot — the quotient int8 "
              "pools shrink")
        gauge("serve_journal_bytes", self.journal_bytes)
        gauge("serve_compile_misses", self.compile_misses)
        if self.recorder is not None:
            counter("serve_trace_events_total", self.recorder.emitted,
                    "flight-recorder events emitted")
            gauge("serve_trace_dropped", self.recorder.dropped,
                  "events the bounded ring has forgotten")
        for name, hist in (("serve_ttft_seconds", self.hist_ttft),
                           ("serve_itl_seconds", self.hist_itl),
                           ("serve_queue_time_seconds", self.hist_queue),
                           ("serve_step_time_seconds", self.hist_step),
                           ("serve_snapshot_seconds",
                            self.hist_snapshot)):
            L.extend(hist.prom_lines(name))
        # per-program wall-time attribution: ONE labeled histogram
        # family (dense buckets like the SLO histograms, so fleet
        # scrape-and-merge stays bucket-exact per program); the TYPE
        # header rides the first member only
        for i, name in enumerate(sorted(self.program_hists)):
            L.extend(self.program_hists[name].prom_lines(
                "serve_program_ms", labels=f'program="{name}"',
                typed=i == 0))
        return "\n".join(L) + "\n"

    def maybe_dump(self, name: str = "serve_metrics") -> Optional[str]:
        """Write the summary as JSON under the IR-dump dir when
        ``TDT_DUMP_IR`` is set (runtime/dump.py — one observability
        switch for kernels AND serving); no-op otherwise."""
        directory = dump.dump_dir()
        if directory is None:
            return None
        path = os.path.join(directory, dump._safe(name) + ".json")
        dump._write(path, json.dumps(self.summary(), indent=2))
        return path


# ---------------------------------------------------------------------------
# THE stats renderings (CLI end-of-run block, periodic one-liner,
# supervisor postmortem) — one formatter, zero drift between surfaces
# ---------------------------------------------------------------------------


def _ms(x) -> str:
    return f"{x * 1e3:.2f} ms" if x is not None else "n/a"


def format_statline(s: dict) -> str:
    """ONE line of live engine state (the ``--stats-every`` periodic log
    and the flight-recorder postmortem header): progress, queue
    pressure, and the SLO percentiles that page an operator."""
    lat = s.get("latency", {})
    ttft = lat.get("ttft", {})
    itl = lat.get("itl", {})

    def p(h, k):
        v = h.get(k)
        return f"{v * 1e3:.1f}" if v is not None else "-"

    line = (f"step {s['steps']} | {s['completed']} done, "
            f"{s['decode']['decode_tokens']} decode toks | "
            f"queue {s.get('max_queue_depth', 0)} peak | "
            f"kv {s.get('peak_kv_utilization', 0.0):.2f} peak | "
            f"ttft p50/p95/p99 {p(ttft, 'p50')}/{p(ttft, 'p95')}/"
            f"{p(ttft, 'p99')} ms | itl p50/p95/p99 {p(itl, 'p50')}/"
            f"{p(itl, 'p95')}/{p(itl, 'p99')} ms")
    progs = s.get("programs") or {}
    if progs:
        # the program eating the most wall time this life (count * mean)
        top = max(progs, key=lambda n: (progs[n]["count"] or 0)
                  * (progs[n]["mean"] or 0.0))
        st = progs[top]
        line += (f" | top program {top} "
                 f"p50 {st['p50']:.2f} ms x{st['count']}")
    return line


def format_stats(s: dict, *, spec: bool = False, prefix: bool = False,
                 failures: bool = False, recovery: bool = False
                 ) -> list[str]:
    """The end-of-run stats block ``examples/serve.py`` prints — moved
    here so every surface (CLI, supervisor, tests) renders ``summary()``
    identically.  Sections beyond the engine/decode core are opt-in by
    flag, matching the CLI's feature gates."""
    lat = s["latency"]
    lines = [
        (f"engine metrics: mean ttft {_ms(s['mean_ttft'])}, "
         f"mean itl {_ms(s['mean_itl'])}, max queue depth "
         f"{s['max_queue_depth']}, peak kv util "
         f"{s['peak_kv_utilization']:.2f}, preemptions "
         f"{s['preemptions']}"),
        (f"latency slo: ttft p50/p95/p99 "
         f"{_ms(lat['ttft']['p50'])}/{_ms(lat['ttft']['p95'])}/"
         f"{_ms(lat['ttft']['p99'])}, itl p50/p95/p99 "
         f"{_ms(lat['itl']['p50'])}/{_ms(lat['itl']['p95'])}/"
         f"{_ms(lat['itl']['p99'])}, step p99 "
         f"{_ms(lat['step']['p99'])}"),
    ]
    kv = s.get("kv")
    if kv and kv.get("token_slots"):
        lines.append(
            f"kv pool: {kv['pool_bytes']} bytes for "
            f"{kv['token_slots']} token slots "
            f"({kv['bytes_per_token']:.1f} B/token, "
            f"{'int8+scales' if kv['quantized'] else 'float'})")
    d = s["decode"]
    lines.append(
        f"decode horizon: {d['dispatches']} dispatches / "
        f"{d['host_syncs']} host syncs for {d['decode_tokens']} "
        f"tokens ({d['decode_steps']} device steps) — "
        f"{d['tokens_per_dispatch']:.2f} tokens/dispatch, "
        f"{d['dispatches_per_token']:.3f} dispatches/token")
    progs = s.get("programs") or {}
    if progs:
        # per-program wall-time attribution (trace_level >= 1), worst
        # total-time first — the step-time decomposition that replaces
        # "which program ate the slow step" archaeology
        by_total = sorted(
            progs, key=lambda n: (progs[n]["count"] or 0)
            * (progs[n]["mean"] or 0.0), reverse=True)
        parts = ", ".join(
            f"{n} p50/p99 {progs[n]['p50']:.2f}/{progs[n]['p99']:.2f} "
            f"x{progs[n]['count']}" for n in by_total[:6])
        lines.append(f"program ms: {parts}")
    if spec:
        sp = s["spec"]
        lines.append(
            f"speculative: {sp['rounds']} fused rounds, accept "
            f"rate {sp['accept_rate']:.2f} (rolling "
            f"{sp['rolling_accept_rate']:.2f}), chosen k "
            f"{sp['chosen_k']}, "
            f"{sp['spec_tokens_per_dispatch']:.2f} spec tokens/"
            f"dispatch, {sp['bailouts']} bailouts"
            + (f", {sp['draft_prefix_skipped_tokens']} draft "
               f"prefill tokens skipped"
               if sp['draft_prefix_skipped_tokens'] else ""))
    if prefix:
        pc = s["prefix_cache"]
        ratio = (f", warm/cold ttft {pc['ttft_warm_over_cold']:.2f}x"
                 if pc.get("ttft_warm_over_cold") is not None else "")
        lines.append(
            f"prefix cache: {pc['lookup_hits']}/{pc['lookups']} "
            f"lookups hit, {pc['prefix_skipped_tokens']} prefill "
            f"tokens skipped, {pc['cached_blocks']} cached / "
            f"{pc['shared_blocks']} shared blocks, "
            f"{pc['cow_copies']} COW, {pc['evictions']} "
            f"evictions{ratio}")
    if failures:
        f = s["failures"]
        lines.append(
            f"failure containment: {f['shed']} shed, "
            f"{f['deadline_expired']} expired, "
            f"{f['quarantined']} quarantined, "
            f"{f['callback_errors']} callback errors, "
            f"{f['forward_retries']} retries / "
            f"{f['forward_bisections']} bisections, "
            f"finish reasons {f['finish_reasons']}")
    if recovery:
        r = s["recovery"]
        lines.append(
            f"crash recovery: {r['snapshots']} snapshots "
            f"(last {r['snapshot_ms_last']:.1f} ms), "
            f"{r['journal_records']} journal records "
            f"({r['journal_bytes']} bytes), "
            f"{r['restored_in_place']} resumed in place / "
            f"{r['restored_requeued']} requeued")
        mg = s.get("migration")
        if mg and (mg["migrated_out"] or mg["migrated_in"]):
            lines.append(
                f"migration: {mg['migrated_out']} drained out, "
                f"{mg['migrated_in']} adopted "
                f"({mg['migrated_in_place']} with live KV), "
                f"{mg['migrated_tokens']} journal tokens carried")
        if mg and (mg.get("pushed_out") or mg.get("pushed_in")):
            lines.append(
                f"disagg push: {mg['pushed_out']} pushed out, "
                f"{mg['pushed_in']} admitted")
    comp = s["compilation"]
    per = ", ".join(f"{n} {c['misses']}c/{c['hits']}h"
                    for n, c in comp["programs"].items())
    lines.append(f"trace cache (compiles/hits): {per}")
    lines.append(
        f"compile stalls: {comp['total_compile_time_s'] * 1e3:.0f} "
        f"ms total, {comp['warmup_compiles']} programs "
        f"({comp['warmup_time_s'] * 1e3:.0f} ms) during warmup")
    return lines
