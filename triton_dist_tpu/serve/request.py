"""Request/response surface of the serving engine.

A :class:`Request` is what a frontend submits: prompt tokens, sampling
knobs, an arrival timestamp, and an optional streaming callback fired as
each token is emitted.  A :class:`RequestOutput` is what the engine
returns at retirement: the emitted tokens, why generation stopped, and
the request's latency metrics (TTFT / inter-token gaps; serve/metrics.py).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np


#: SLO classes, best-first.  The position in this tuple is the class's
#: RANK (0 = most latency-sensitive): class-aware admission prefers the
#: lowest rank, victim picking and the brownout ladder spend the highest
#: rank first (docs/serving.md "Overload, SLO classes & autoscaling").
SLO_CLASSES = ("interactive", "batch", "best_effort")


def slo_rank(slo_class: str) -> int:
    """Rank of an SLO class (0 = interactive = most protected)."""
    return SLO_CLASSES.index(slo_class)


class FinishReason(enum.Enum):
    LENGTH = "length"      # hit max_new_tokens
    EOS = "eos"            # emitted params.eos_id (included in the output)
    ABORT = "abort"        # cancelled by the caller
    DEADLINE = "deadline"  # params.deadline_s passed before decode began
    SHED = "shed"          # rejected at submit(): waiting queue at bound
    ERROR = "error"        # quarantined (RequestOutput.error says why)


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (the serving twin of
    ``models.sampling.make_sampler``).

    ``temperature == 0`` is greedy argmax; otherwise tokens draw from the
    temperature → top-k → top-p filtered distribution with a per-request
    PRNG stream (``seed``), folded per emitted token — so a preempted and
    recomputed request keeps drawing the SAME stream where it left off.

    ``deadline_s`` is a TTL against the engine clock: a request still
    WAITING or mid-PREFILL ``deadline_s`` seconds after arrival is swept
    and retired with ``FinishReason.DEADLINE`` (once decoding, it runs
    to completion — abandoning in-flight work would waste the prefill it
    already paid for).
    """

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    deadline_s: Optional[float] = None

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {self.deadline_s}")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    def to_dict(self) -> dict:
        """JSON-safe form for the recovery token journal — everything
        deterministic replay needs, notably ``seed`` (the per-token
        ``fold_in`` stream) and ``deadline_s`` (restore re-bases the
        remaining TTL onto the new engine clock)."""
        return {
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "eos_id": self.eos_id,
            "seed": self.seed,
            "deadline_s": self.deadline_s,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SamplingParams":
        return cls(**d)


@dataclass
class Request:
    """One generation request.

    ``on_token(request_id, token)`` (optional) streams each emitted token
    the moment the engine commits it — before the request retires.
    ``arrival_time`` defaults to the engine clock at ``submit()``.
    ``trace`` is the distributed-tracing context (docs/observability.md
    "Fleet observability"): ``{"trace_id": <fleet-unique id>, "hop":
    <0-based life count of the journey>}``.  The fleet controller stamps
    it at admission; a bare engine defaults it at ``submit()`` — either
    way it rides migration manifests and the token journal, so a
    request's journey stays one trace across replicas and restarts.

    ``slo_class`` (:data:`SLO_CLASSES`) tags the request's service tier.
    The default ``"interactive"`` keeps all-default traffic exactly as
    before: with every request in one class, class-aware admission and
    victim picking reduce to the original FCFS/LIFO orders bit-for-bit.
    The tag rides the journal, migration manifests, and the wire, so a
    request keeps its tier across replicas and restarts.

    ``on_finish(output)`` (optional) fires EXACTLY ONCE at retirement —
    whichever layer retires the request (engine step, deadline sweep,
    admission shed, fleet-queue shed) and however it ends.  This is the
    terminal notification a streaming frontend needs: ``on_token`` says
    nothing for a zero-token retirement (shed/deadline), so without it a
    shed request's consumer would wait forever.
    """

    request_id: str
    prompt: np.ndarray  # [S0] int32 token ids
    params: SamplingParams = field(default_factory=SamplingParams)
    arrival_time: Optional[float] = None
    on_token: Optional[Callable[[str, int], None]] = None
    trace: Optional[dict] = None
    slo_class: str = "interactive"
    on_finish: Optional[Callable[["RequestOutput"], None]] = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.request_id}: empty prompt")
        if self.slo_class not in SLO_CLASSES:
            raise ValueError(
                f"request {self.request_id}: unknown slo_class "
                f"{self.slo_class!r} (expected one of {SLO_CLASSES})")


@dataclass
class RequestOutput:
    """The engine's answer: emitted tokens + why it stopped + latencies.

    ``error`` carries the failure string for quarantined (``ERROR``),
    shed (``SHED``) and expired (``DEADLINE``) retirements; ``None`` on
    the healthy finish reasons."""

    request_id: str
    prompt: np.ndarray
    token_ids: list[int]
    finish_reason: FinishReason
    metrics: "RequestMetrics"  # serve/metrics.py (quoted: no import cycle)
    error: Optional[str] = None

    @property
    def n_generated(self) -> int:
        return len(self.token_ids)


def make_requests(prompts: Sequence[Sequence[int]], *,
                  params: SamplingParams | None = None,
                  prefix: str = "req") -> list[Request]:
    """Convenience: wrap raw prompt token lists into numbered requests."""
    params = params or SamplingParams()
    return [Request(request_id=f"{prefix}-{i}",
                    prompt=np.asarray(p, np.int32), params=params)
            for i, p in enumerate(prompts)]
