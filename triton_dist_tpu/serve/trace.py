"""Engine flight recorder: structured event tracing for the serving loop.

Every interesting engine decision — admission, prefill chunking, horizon
drains, spec rounds, COW splits, preemption, quarantine, bailout,
snapshot — used to happen invisibly inside the step loop; diagnosing a
tail-latency spike or a chaos-test failure meant re-running under a
debugger.  This module makes the engine's timeline a first-class
artifact, three ways:

- **Ring buffer** (:class:`FlightRecorder`): a bounded deque of typed,
  timestamped events, each carrying the PR 5 monotonic step index, the
  request id(s) involved, and a small payload (chunk size, chosen k,
  accept count, blocks touched).  Hot-path discipline: ``emit`` is an
  append to a bounded ring — no device sync, no I/O, no string
  formatting — and a single ``level`` knob gates it off entirely
  (``bench_serve --trace`` measures the overhead; ``PERF_FLOORS.json``
  holds ``serve_trace_overhead`` >= 0.95).

- **Perfetto export** (:meth:`FlightRecorder.to_perfetto`): per-request
  lifecycle *spans* (queue → prefill → decode, re-opened across
  preemptions) reconstructed from the event stream as a Chrome trace,
  pid-namespaced so :func:`runtime.profiling.merge_rank_traces` merges
  the engine timeline with the device profiler's into ONE
  ui.perfetto.dev view (:meth:`export_profile` drops the file where the
  merge globs it).

- **Postmortem flush** (:meth:`FlightRecorder.flush`): on any
  fault/quarantine/watchdog/crash path the engine writes the ring to
  ``flight_<step>.json`` (under ``TDT_DUMP_IR`` or the snapshot dir) so
  the PR 5 supervisor and the chaos harness get a trail; the tail of
  the ring also rides snapshots (serve/recovery.py), so a restored
  engine carries its previous life's provenance.

The taxonomy is CLOSED over the engine's failure surface: every
:class:`serve.request.FinishReason` retires through a ``retire`` event
(:data:`RETIRE_REASONS`), and every ``runtime/faults.py`` injection
point lands in the ring as a ``fault`` event
(:data:`FAULT_POINT_EVENTS`) — a meta-test cross-checks both sets
against the source so a new failure path cannot silently skip the
recorder.  See docs/observability.md for the event reference and the
Perfetto recipe.
"""

from __future__ import annotations

import gzip
import json
import math
import os
import time
from collections import deque
from typing import Optional

# ---------------------------------------------------------------------------
# Event taxonomy
# ---------------------------------------------------------------------------

#: Every event type the recorder may emit (docs/observability.md).
EVENT_TYPES = frozenset({
    "submit",         # request entered the engine (or was shed at the door)
    "admit",          # WAITING -> PREFILL: slot + blocks granted
    "prefill_chunk",  # one chunked-prefill dispatch (level >= 2 only)
    "prefill_done",   # prompt fully prefilled; row joins the decode batch
    "decode_drain",   # one decode drain (single-step batch or horizon link)
    "spec_round",     # one fused speculative round drained
    "preempt",        # LIFO eviction back to the waiting queue
    "cow_split",      # copy-on-write block split before a shared-page write
    "evict",          # prefix-cache tier block reclaimed under pressure
    "snapshot",       # durable engine capture published
    "restore",        # engine rebuilt from snapshot + journal
    "fault",          # an injected/contained/engine-level failure seam fired
    "bailout",        # speculative chain failed; spec latched off
    "retire",         # request finished (reason = any FinishReason value)
    # fleet serving (serve/fleet.py, docs/serving.md "Fleet serving"):
    # migration rides the engine ring on BOTH sides of a hand-off, and
    # the FleetController keeps its own recorder for routing + replica
    # lifecycle (one timeline per surface, same event vocabulary).
    "migrate_out",    # request handed off to another replica (drain)
    "migrate_in",     # request adopted from a migration manifest
    "route",          # fleet router placed a request on a replica
    "replica_state",  # replica HEALTHY -> SUSPECT -> DEAD transitions
    # disaggregated prefill->decode tier (serve/disagg.py,
    # docs/serving.md "Disaggregated serving"): the per-request
    # KV-page PUSH at prefill completion — the drain/migrate machinery
    # under a distinct name, so tier hand-offs and failure migrations
    # read apart on one timeline.
    "push_out",       # prefill replica pushed a request's KV hand-off
    "push_in",        # decode replica admitted a pushed request
    # network serving plane (serve/net.py, docs/serving.md "Network
    # fleet serving"): the RemoteReplica client's ring records every
    # retried call, so a postmortem shows the backoff ladder a
    # partition actually drove.
    "net_retry",      # a network call failed and will retry under backoff
    # overload robustness (docs/serving.md "Overload, SLO classes &
    # autoscaling"): the engine's graceful-degradation ladder and the
    # fleet's pressure-driven scaling — every degrade/scale decision
    # lands on a timeline next to the traffic it shaped.
    "brownout",       # engine ladder moved a rung (data: rung, prev)
    "scale",          # fleet autoscaler spawned/retired a replica
    "ingress_shed",   # fleet token-bucket refused a request at the door
    # state integrity (docs/serving.md "Durability & integrity"): a
    # durable or wire artifact FAILED verification — journal interior
    # corruption salvaged + quarantined at restore, a snapshot leaf
    # digest mismatch, or a wire manifest rejected by its receiver.
    # Data names the artifact class and what the salvage kept/lost.
    "corrupt",        # artifact integrity check failed (never adopted)
})

#: FinishReason values the ``retire`` event is specified over — the
#: meta-test asserts every ``serve.request.FinishReason`` member is here,
#: so a new retirement reason must be registered with the recorder.
RETIRE_REASONS = frozenset({
    "length", "eos", "abort", "deadline", "shed", "error",
})

#: Every ``FaultInjector`` point (plus the engine-level seams that fire
#: without the injector) mapped to the event type that records it.  The
#: meta-test greps the source tree for ``.fire("<point>"`` calls and
#: asserts each point appears here.
FAULT_POINT_EVENTS = {
    "forward": "fault",       # engine device-dispatch seam
    "block_alloc": "fault",   # BlockManager.ensure grow path
    "callback": "fault",      # the on_token invocation seam
    "clock": "fault",         # wrap_clock readings (skew)
    "snapshot": "fault",      # the two snapshot crash windows
    "watchdog": "fault",      # step watchdog trip (engine-level, no
                              # injector point — WatchdogTimeout)
    "crash": "fault",         # anything escaping step() (InjectedKill,
                              # escalations, interrupts)
    "net": "fault",           # network serving plane seams (serve/net.py:
                              # client send, server receive, server
                              # respond — drop/delay/duplicate/partition)
    "integrity": "fault",     # artifact corruption seams (journal-line
                              # append, snapshot tmp-dir leaf, wire
                              # manifest blob — bitflip/truncate/zero);
                              # the DETECTION lands as a "corrupt" event
                              # on whichever surface caught it
}

#: pid the engine timeline claims in exported Chrome traces.  Below the
#: Linux pid cap (4194304) so :func:`runtime.profiling.merge_rank_traces`'s
#: per-rank re-namespacing (rank * 10_000_000 + pid) stays injective
#: against real process pids.
ENGINE_PID = 3_999_999

#: Newest ring events a migration manifest carries per request — both
#: producers share it: the live ``ServeEngine.drain`` gathers the tail
#: from its ring, the crash-path ``recovery.manifest_from_journal``
#: recovers it from the dead life's flight file.  Bounded so a manifest
#: cannot grow with ring capacity (docs/observability.md "Fleet
#: observability").
MIGRATE_EVENT_TAIL = 128

#: pid of the fleet controller's own timeline in a merged fleet export
#: (serve/fleet.py), and the base pid replica ``r<i>`` claims
#: (``FLEET_REPLICA_PID_BASE + i``).  All below the Linux pid cap for
#: the same merge-injectivity reason as :data:`ENGINE_PID`.
FLEET_PID = 3_999_998
FLEET_REPLICA_PID_BASE = 3_900_000


# ---------------------------------------------------------------------------
# Log-bucketed histograms (the bounded replacement for per-request
# latency lists)
# ---------------------------------------------------------------------------


class LogHistogram:
    """Log-bucketed scalar histogram: O(buckets) memory regardless of
    sample count, percentiles within one bucket's relative width.

    Buckets span ``[lo, hi)`` with ``per_decade`` buckets per decade
    (default 24 → ~10% wide, so p50/p95/p99 land within ~5% of numpy's
    on the same samples — pinned by tests/test_serve_trace.py).  Values
    below ``lo`` (including 0 and negatives — fake test clocks produce
    them) land in the underflow bucket; values past ``hi`` in the
    overflow bucket.  ``sum``/``count``/``min``/``max`` track exact
    values, so the mean is exact even though percentiles are bucketed.
    """

    def __init__(self, lo: float = 1e-6, hi: float = 4000.0,
                 per_decade: int = 24):
        if lo <= 0 or hi <= lo:
            raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
        self.lo = lo
        self.per_decade = per_decade
        self._log_lo = math.log10(lo)
        n = int(math.ceil(math.log10(hi / lo) * per_decade))
        # counts[0] = underflow (< lo); counts[1 + i] covers
        # [edge(i), edge(i + 1)); counts[-1] = overflow (>= hi)
        self.counts = [0] * (n + 2)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def edge(self, i: int) -> float:
        """Upper edge of bucket ``i`` (0-based over the log range)."""
        return self.lo * 10.0 ** ((i + 1) / self.per_decade)

    def observe(self, x: float) -> None:
        x = float(x)
        self.count += 1
        self.sum += x
        if x < self.min:
            self.min = x
        if x > self.max:
            self.max = x
        if x < self.lo:
            self.counts[0] += 1
            return
        i = 1 + int((math.log10(x) - self._log_lo) * self.per_decade)
        self.counts[min(i, len(self.counts) - 1)] += 1

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def percentile(self, p: float) -> Optional[float]:
        """Approximate p-th percentile: the geometric midpoint of the
        bucket holding the rank (underflow reports ``min``, overflow
        ``max`` — both exact)."""
        if not self.count:
            return None
        rank = max(1, int(-(-p / 100.0 * self.count // 1)))  # ceil
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                if i == 0:
                    return self.min
                if i == len(self.counts) - 1:
                    return self.max
                hi = self.edge(i - 1)
                lo = hi / 10.0 ** (1.0 / self.per_decade)
                return (lo * hi) ** 0.5
        return self.max

    def stats(self) -> dict:
        """The summary() view: count/mean plus the SLO percentiles."""
        return {
            "count": self.count,
            "mean": self.mean,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
            "max": self.max if self.count else None,
        }

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Fold ``other`` into this histogram EXACTLY: identical bucket
        schemes add count-wise, so the merged percentiles equal those of
        a histogram fed the pooled samples bucket-exactly, and
        sum/count/min/max stay exact — the fleet aggregation primitive
        (serve/fleet.py; a mean-of-percentiles would be wrong, this is
        a percentile-of-merged-counts).  Raises on a bucket-scheme
        mismatch: adding misaligned buckets would silently corrupt the
        quantiles."""
        if (self.lo != other.lo or self.per_decade != other.per_decade
                or len(self.counts) != len(other.counts)):
            raise ValueError(
                f"histogram bucket schemes differ: "
                f"(lo={self.lo}, per_decade={self.per_decade}, "
                f"n={len(self.counts)}) vs (lo={other.lo}, "
                f"per_decade={other.per_decade}, n={len(other.counts)})")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        return self

    def bucket_index(self, le: float) -> int:
        """Index of the bucket whose upper edge is ``le`` (inverse of
        the exposition's edge math; tolerant of float round-trips —
        buckets are ~10% apart, a ``%.6g`` parse-back is ~1e-6 off)."""
        if le <= self.lo * 10.0 ** (0.5 / self.per_decade):
            return 0
        i = int(round((math.log10(le) - self._log_lo) * self.per_decade))
        return max(0, min(i, len(self.counts) - 2))

    @classmethod
    def from_prom(cls, series: dict, name: str, *,
                  labels: str = "",
                  lo: float = 1e-6, hi: float = 4000.0,
                  per_decade: int = 24) -> "LogHistogram":
        """Rebuild a histogram from its own text exposition (a
        ``parse_prometheus`` dict) — the subprocess half of fleet
        aggregation (scrape-and-merge).  The exposition's cumulative
        buckets de-accumulate back into per-bucket counts on the SAME
        scheme, so a scrape-reconstructed histogram merges bucket-
        exactly with a live one; ``_sum``/``_count`` and the
        ``_min``/``_max`` gauges restore the exact scalar fields.
        ``labels`` selects one series of a labeled histogram family
        (e.g. ``'program="paged_decode"'`` for ``serve_program_ms`` —
        the exact label text :meth:`prom_lines` emitted)."""
        h = cls(lo=lo, hi=hi, per_decade=per_decade)
        lab = f"{{{labels}}}" if labels else ""
        h.count = int(series.get(f"{name}_count{lab}", 0))
        h.sum = float(series.get(f"{name}_sum{lab}", 0.0))
        if h.count:
            h.min = float(series.get(f"{name}_min{lab}", float("inf")))
            h.max = float(series.get(f"{name}_max{lab}", float("-inf")))
        buckets = []
        inner = f"{labels}," if labels else ""
        prefix = f"{name}_bucket{{{inner}le=\""
        for key, v in series.items():
            if key.startswith(prefix) and not key.startswith(
                    f"{name}_bucket{{{inner}le=\"+Inf"):
                buckets.append((float(key[len(prefix):-2]), int(v)))
        buckets.sort()
        acc = 0
        for le, cum in buckets:
            h.counts[h.bucket_index(le)] = cum - acc
            acc = cum
        h.counts[-1] = h.count - acc   # overflow: past the last edge
        return h

    def prom_lines(self, name: str, *, labels: str = "",
                   typed: bool = True) -> list[str]:
        """Prometheus text-exposition lines for this histogram —
        DENSE cumulative ``_bucket{le=}`` (EVERY bucket edge in the
        scheme, zero-traffic ones included, plus ``+Inf``), then
        ``_sum``/``_count`` and exact ``_min``/``_max`` gauges.
        ``labels`` prepends extra label pairs to every bucket and
        suffixes the scalar series (the ``serve_program_ms{program=}``
        family); ``typed=False`` suppresses the ``# TYPE`` header so a
        labeled family emits it once, on its first member.

        Dense matters for aggregation: every engine shares one bucket
        scheme, so every replica's exposition carries the IDENTICAL
        full ``le`` label set — a recording rule's ``sum by (le)`` (and
        :meth:`from_prom` scrape-and-merge) stays monotone and complete
        even when the replicas reached different depths.  Sparse
        nonzero-only buckets broke exactly that: a replica missing an
        intermediate ``le`` made the cross-instance sum non-monotone,
        and stopping at each replica's own deepest reached bucket would
        still drop its total from the deeper sums
        (tests/test_serve_fleet.py pins the merged-vs-pooled bucket
        equality).  Cost: ~230 lines per histogram — a few tens of KB
        per scrape, the price of correct `histogram_quantile` over
        `sum by (le)`."""
        out = [f"# TYPE {name} histogram"] if typed else []
        inner = f"{labels}," if labels else ""
        lab = f"{{{labels}}}" if labels else ""
        acc = 0
        for i in range(len(self.counts) - 1):
            acc += self.counts[i]
            le = self.lo if i == 0 else self.edge(i - 1)
            out.append(f'{name}_bucket{{{inner}le="{le:.6g}"}} {acc}')
        out.append(f'{name}_bucket{{{inner}le="+Inf"}} {self.count}')
        # .17g: enough digits to round-trip a float64 exactly, so a
        # scrape reconstruction (from_prom) recovers sum/min/max EXACTLY
        out.append(f"{name}_sum{lab} {self.sum:.17g}")
        out.append(f"{name}_count{lab} {self.count}")
        if self.count:
            # exact extremes ride as gauges so a scrape reconstruction
            # (from_prom) merges with exact min/max, not bucket edges
            if typed:
                out.append(f"# TYPE {name}_min gauge")
            out.append(f"{name}_min{lab} {self.min:.17g}")
            if typed:
                out.append(f"# TYPE {name}_max gauge")
            out.append(f"{name}_max{lab} {self.max:.17g}")
        return out


# ---------------------------------------------------------------------------
# Event-stream views (module-level so the fleet controller can render
# ANY event list — a live ring, a flight-file postmortem, a carried
# migration tail — not just its own recorder's)
# ---------------------------------------------------------------------------


def spans_from_events(evs: list) -> dict:
    """Per-request lifecycle spans from a SORTED event stream:
    ``{rid: [(phase, t0, t1), ...]}`` with phases ``queue``
    (submit→admit, re-opened by preemption), ``prefill``
    (admit→prefill_done) and ``decode`` (prefill_done→retire).  A phase
    still open at the newest event closes there (an in-flight request's
    span is the stream's honest horizon)."""
    if not evs:
        return {}
    end = evs[-1][0]
    out: dict[str, list] = {}
    open_: dict[str, tuple] = {}   # rid -> (phase, t0)

    def close(rid, ts):
        ph = open_.pop(rid, None)
        if ph is not None:
            out.setdefault(rid, []).append((ph[0], ph[1], ts))

    for ts, step, etype, rid, data in evs:
        if rid is None:
            continue
        if etype == "submit":
            close(rid, ts)
            open_[rid] = ("queue", ts)
        elif etype == "admit":
            close(rid, ts)
            open_[rid] = ("prefill", ts)
        elif etype == "prefill_done":
            close(rid, ts)
            open_[rid] = ("decode", ts)
        elif etype == "preempt":
            close(rid, ts)
            open_[rid] = ("queue", ts)
        elif etype == "migrate_out":
            # the request LEFT this timeline: close without reopening,
            # or the source track would render it active until the
            # stream horizon — hours after it migrated away
            close(rid, ts)
        elif etype == "migrate_in":
            # the journey continues HERE: the carried tail seeded ahead
            # of this event holds the source-side phases, and the
            # adopted row is decoding (in place) or re-queued — either
            # way a fresh span opens at the adoption instant
            close(rid, ts)
            open_[rid] = ("decode" if (data or {}).get("in_place")
                          else "queue", ts)
        elif etype == "retire":
            close(rid, ts)
            out.setdefault(rid, [])
    for rid in list(open_):
        close(rid, end)
    return out


def events_to_perfetto(events: list, *, pid: int = ENGINE_PID,
                       process_name: str =
                       "serve engine (flight recorder)",
                       tids_out: Optional[dict] = None) -> list[dict]:
    """Render one event stream as Chrome-trace events under ``pid``:
    a process_name meta, one thread per request with its whole-request
    span enclosing the lifecycle phase spans, and instants for point
    events.  The fleet merge (serve/fleet.py) calls this once per
    replica with a distinct pid, so one file holds every replica's
    timeline side by side; :meth:`FlightRecorder.to_perfetto` is the
    single-engine wrapper.  ``tids_out`` (optional dict) is filled with
    the ``rid -> tid`` assignment so :func:`link_migration_flows` can
    anchor flow arrows on the request's own thread (a flow event on a
    slice-less tid would not bind in ui.perfetto.dev)."""
    evs = sorted(events, key=lambda e: (e[0], e[1]))
    trace: list[dict] = [{
        "ph": "M", "pid": pid, "tid": 0,
        "name": "process_name",
        "args": {"name": process_name},
    }]
    tids: dict[str, int] = {}

    def tid_of(rid):
        if rid not in tids:
            tids[rid] = len(tids) + 1
            trace.append({"ph": "M", "pid": pid,
                          "tid": tids[rid], "name": "thread_name",
                          "args": {"name": rid}})
        return tids[rid]

    def us(ts):
        return ts * 1e6

    # Whole-request spans enclose the phase spans (first event ->
    # retire / stream horizon).
    first: dict[str, float] = {}
    last: dict[str, float] = {}
    for ts, step, etype, rid, data in evs:
        if rid is None:
            continue
        first.setdefault(rid, ts)
        last[rid] = ts
    for rid, phases in spans_from_events(evs).items():
        t0, t1 = first[rid], last[rid]
        trace.append({"ph": "X", "pid": pid,
                      "tid": tid_of(rid), "cat": "request",
                      "name": f"request {rid}", "ts": us(t0),
                      "dur": max(us(t1) - us(t0), 1.0)})
        for name, p0, p1 in phases:
            trace.append({"ph": "X", "pid": pid,
                          "tid": tid_of(rid), "cat": "phase",
                          "name": name, "ts": us(p0),
                          "dur": max(us(p1) - us(p0), 1.0)})
    for ts, step, etype, rid, data in evs:
        if etype in ("submit", "admit", "prefill_done"):
            continue  # phase boundaries, already spans
        args = {"step": step}
        if data:
            args.update(data)
        trace.append({"ph": "i", "s": "t" if rid else "g",
                      "pid": pid,
                      "tid": tid_of(rid) if rid else 0,
                      "cat": "engine", "name": etype, "ts": us(ts),
                      "args": args})
    if tids_out is not None:
        tids_out.update(tids)
    return trace


def link_migration_flows(sources: list,
                         tids: Optional[dict] = None) -> list[dict]:
    """Perfetto flow arrows for cross-replica request journeys.

    ``sources`` is ``[(pid, events), ...]`` — one entry per replica
    timeline already rendered into a merged file; ``tids`` maps
    ``pid -> {rid: tid}`` (the ``tids_out`` of each
    :func:`events_to_perfetto` call) so the arrows anchor on the
    request's own thread, where its slices live — Perfetto binds a
    flow event to the slice enclosing its timestamp on the same
    pid/tid, so a slice-less tid would drop the arrow.  For every
    ``migrate_in`` (or disagg ``push_in``) event, emit a flow-start
    (``ph: "s"``) anchored at
    the hand-off point on the SOURCE replica and a flow-finish
    (``ph: "f"``) at the adoption instant on the target, sharing one
    flow id — ui.perfetto.dev draws the arrow, making a migrated
    request ONE connected journey across replica tracks.

    The source anchor prefers the exact ``migrate_out`` twin (the
    cooperative drain path emits one, carrying the same ``flow`` id);
    on the crash path the source process died before any
    ``migrate_out`` could be recorded, so the anchor falls back to the
    source's newest event for that rid preceding the adoption (the
    postmortem flight file is where those events survive)."""
    flows: list[dict] = []
    # index: flow id -> (pid, ts) of the matching migrate_out
    out_by_flow: dict = {}
    # rid -> [(ts, pid)] of every event, for the crash-path fallback
    rid_events: dict = {}
    for pid, events in sources:
        for ev in sorted(events, key=lambda e: (e[0], e[1])):
            ts, step, etype, rid, data = ev
            if rid is not None:
                rid_events.setdefault(rid, []).append((ts, pid))
            if (etype in ("migrate_out", "push_out")
                    and data and data.get("flow")):
                out_by_flow[data["flow"]] = (pid, ts)

    def emit(ph, pid, rid, ts, fid, **extra):
        flows.append({"ph": ph, "pid": pid,
                      "tid": (tids or {}).get(pid, {}).get(rid, 0),
                      "cat": "migration", "name": "migrate",
                      "id": fid, "args": {"rid": rid},
                      "ts": ts * 1e6, **extra})

    for pid, events in sources:
        for ts, step, etype, rid, data in events:
            if etype not in ("migrate_in", "push_in") or rid is None:
                continue
            fid = (data or {}).get("flow") or f"{rid}#?"
            src = out_by_flow.get(fid)
            if src is None:
                # crash path: anchor at the newest source-side event
                # before the adoption, on a DIFFERENT pid
                cands = sorted((t, p) for t, p in rid_events.get(rid, ())
                               if p != pid and t <= ts)
                src = (cands[-1][1], cands[-1][0]) if cands else None
            if src is None:
                continue
            emit("s", src[0], rid, src[1], fid)
            emit("f", pid, rid, ts, fid, bp="e")
    return flows


# ---------------------------------------------------------------------------
# The flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of typed engine events (module docstring).

    ``level`` gates the hot path: 0 records nothing (``emit`` returns
    before touching the ring), 1 records lifecycle + failure events,
    2 adds per-dispatch detail (``prefill_chunk``).  ``capacity`` bounds
    memory — the ring drops its oldest events, ``dropped`` counts them.

    Events are plain tuples ``(ts, step, type, rid, data)``: ``ts`` is
    wall time (``time.monotonic`` — deliberately NOT the engine clock,
    which chaos tests fake and the injector's ``clock`` point meters),
    ``step`` the engine's monotonic iteration index, ``rid`` a request
    id or ``None`` for engine-scoped events, ``data`` a small dict or
    ``None``.
    """

    def __init__(self, capacity: int = 4096, level: int = 1,
                 clock=time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.level = int(level)
        self._clock = clock
        self._ring: deque = deque(maxlen=capacity)
        self.step = 0
        self.emitted = 0

    # -- hot path ---------------------------------------------------------

    def set_step(self, step: int) -> None:
        self.step = step

    def emit(self, etype: str, rid: Optional[str] = None,
             **data) -> None:
        """Append one event — ring append only (the hot-path contract)."""
        if self.level <= 0:
            return
        self.emitted += 1
        self._ring.append((self._clock(), self.step, etype, rid,
                           data or None))

    @property
    def dropped(self) -> int:
        """Events the bounded ring has already forgotten."""
        return self.emitted - len(self._ring)

    # -- views ------------------------------------------------------------

    def events(self) -> list[tuple]:
        return list(self._ring)

    def tail(self, n: int = 256) -> list[list]:
        """The newest ``n`` events, JSON-safe (rides snapshots and the
        postmortem flush)."""
        evs = list(self._ring)[-n:]
        return [[float(ts), int(step), etype, rid, data]
                for ts, step, etype, rid, data in evs]

    def seed(self, events) -> None:
        """Re-append events carried across a restore (snapshot tail) —
        the restored engine's ring then holds its previous life's trail
        ahead of its own events."""
        for ev in events:
            try:
                ts, step, etype, rid, data = ev
            except (TypeError, ValueError):
                continue
            self.emitted += 1
            self._ring.append((float(ts), int(step), str(etype), rid,
                               data))

    # -- per-request lifecycle spans --------------------------------------

    def spans(self, evs: Optional[list] = None) -> dict:
        """Reconstruct per-request lifecycle spans from the event
        stream: ``{rid: [(phase, t0, t1), ...]}`` with phases ``queue``
        (submit→admit, re-opened by preemption), ``prefill``
        (admit→prefill_done) and ``decode`` (prefill_done→retire).  A
        phase still open at the newest event closes there (an in-flight
        request's span is the ring's honest horizon).  ``evs`` lets a
        caller pass ONE snapshot of the ring (``to_perfetto`` does — the
        engine may be emitting concurrently, and two reads of the live
        deque could disagree on which requests exist)."""
        if evs is None:
            evs = sorted(self._ring, key=lambda e: (e[0], e[1]))
        return spans_from_events(evs)

    # -- Perfetto / Chrome trace export -----------------------------------

    def to_perfetto(self) -> dict:
        """The ring as a Chrome trace (``{"traceEvents": [...]}``):
        one thread per request carrying its lifecycle phase spans
        (``ph: "X"``) under a whole-request span, instants (``ph: "i"``)
        for point events, all on :data:`ENGINE_PID` so
        ``runtime.profiling.merge_rank_traces`` folds the engine
        timeline into the device profiler's merged view."""
        return {"traceEvents": events_to_perfetto(list(self._ring))}

    def export_perfetto(self, path: str) -> str:
        """Write :meth:`to_perfetto` to ``path`` (gzipped when the name
        ends ``.gz`` — the profiler's own trace format)."""
        return write_trace(self.to_perfetto(), path)

    def export_profile(self, job_dir: str, rank: int = 0) -> str:
        """Drop the engine timeline where
        :func:`runtime.profiling.merge_rank_traces` globs per-rank
        traces (``{job_dir}/rank{rank}/engine.trace.json.gz``) — run a
        ``group_profile`` capture into the same ``job_dir``, call this,
        then merge: ONE ui.perfetto.dev file holds the device timeline
        and the engine's side by side (docs/observability.md has the
        recipe)."""
        out = os.path.join(job_dir, f"rank{rank}", "engine.trace.json.gz")
        return self.export_perfetto(out)

    # -- postmortem flush -------------------------------------------------

    def flush(self, directory: str, *, reason: str,
              statline: Optional[str] = None,
              extra: Optional[dict] = None) -> str:
        """Write the ring to ``{directory}/flight_<step>.json`` — the
        postmortem trail for the supervisor and the chaos harness.  Only
        called OFF the hot path (fault/quarantine/watchdog/crash seams);
        best-effort durable (flush + fsync) so the file survives the
        process dying right after.  ``extra`` merges additional JSON-safe
        sections into the document (the fleet controller rides its
        router decision audit along — serve/fleet.py)."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"flight_{self.step}.json")
        doc = {
            "reason": reason,
            "step": self.step,
            "wall": time.time(),
            "emitted": self.emitted,
            "dropped": self.dropped,
            "statline": statline,
            "events": self.tail(self.capacity),
        }
        if extra:
            doc.update(extra)
        from triton_dist_tpu.serve.integrity import atomic_write_json
        # JSON-safe normalization first (ring events may carry numpy
        # scalars etc. — the old ``default=str`` behavior), then the
        # shared digest-stamping atomic writer: the postmortem file is
        # read back on the crash path (manifest_from_journal's event
        # tails), so it gets the same integrity framing as every other
        # durable serving artifact.
        doc = json.loads(json.dumps(doc, default=str))
        try:
            return atomic_write_json(path, doc)
        except OSError:
            return path  # best-effort durable, as before


def write_trace(doc: dict, path: str) -> str:
    """Write a Chrome-trace document to ``path`` (gzipped when the name
    ends ``.gz`` — the device profiler's own format, so the file lands
    wherever ``merge_rank_traces`` globs)."""
    text = json.dumps(doc, default=str)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    if path.endswith(".gz"):
        with gzip.open(path, "wt") as f:
            f.write(text)
    else:
        with open(path, "w") as f:
            f.write(text)
    return path


def load_flight(path: str) -> dict:
    """Read a :meth:`FlightRecorder.flush` postmortem file.  Raises
    :class:`ValueError` on a whole-document digest mismatch (readers on
    the crash path already treat an unreadable flight file as
    best-effort-absent); pre-integrity files carry no digest and load
    unverified."""
    from triton_dist_tpu.serve.integrity import DOC_CRC, verify_json_doc
    with open(path) as f:
        doc = json.load(f)
    if verify_json_doc(doc) is False:
        raise ValueError(f"flight file {path}: digest mismatch")
    doc.pop(DOC_CRC, None)
    return doc


def latest_flight(directory: str) -> Optional[str]:
    """Newest ``flight_*.json`` under ``directory`` (what the
    supervisor surfaces after a crash), or ``None``."""
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith("flight_") and n.endswith(".json")]
    except OSError:
        return None
    if not names:
        return None
    paths = [os.path.join(directory, n) for n in names]
    return max(paths, key=os.path.getmtime)


# ---------------------------------------------------------------------------
# Live metrics endpoint (Prometheus text exposition over stdlib HTTP)
# ---------------------------------------------------------------------------


def start_metrics_server(metrics, port: int = 0, host: str = "127.0.0.1"):
    """Serve ``metrics.to_prometheus()`` at ``/metrics`` from a daemon
    thread (``examples/serve.py --metrics-port``).  Returns the server;
    ``server.server_address[1]`` is the bound port (pass 0 to pick a
    free one).  Stdlib only — no new dependency rides the image."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — stdlib handler contract
            if self.path.rstrip("/") in ("", "/metrics".rstrip("/"),
                                         "/metrics"):
                try:
                    body = metrics.to_prometheus().encode()
                except Exception as e:  # noqa: BLE001 — the endpoint
                    # must answer even when a gauge source is mid-update
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(repr(e).encode())
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):  # quiet: the engine's stdout is
            pass                       # the serving log

    srv = http.server.ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="serve-metrics")
    t.start()
    return srv
